"""ConfigFactory + the scheduler loop: the shell around the algorithm.

Parity target: reference plugin/pkg/scheduler/factory/factory.go (671 ln) and
scheduler.go (156 ln):

- 8 informer feeds (factory.go:98-150): unassigned pods -> FIFO, assigned
  pods -> cache, nodes -> cache + lister, services/RCs/RSs/PVs/PVCs -> listers
- multi-scheduler dispatch by pod's scheduler name (factory.go:426-432)
- scheduleOne (scheduler.go:93-155): blocking NextPod -> Schedule ->
  AssumePod (optimistic, 30s TTL) -> async Bind; on error: FailedScheduling
  event + PodScheduled=False condition + exponential backoff requeue
  (factory.go:503-539, 1s -> 60s)
- metrics: e2e/algorithm/binding latency histograms (metrics/metrics.go)
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.api import fields as fieldsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import FIFO, Informer, ListWatch, RESTClient
from kubernetes_tpu.client.cache import node_name_indexer
from kubernetes_tpu.client.listers import (
    ControllerLister, NodeLister, PodLister, ReplicaSetLister, ServiceLister,
)
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.registry.generic import set_pod_condition
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.extender import extenders_from_config
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler
from kubernetes_tpu.scheduler.provider import (
    DEFAULT_PROVIDER, PluginArgs, get_predicates, get_priorities, get_provider,
    load_policy,
)
from kubernetes_tpu.utils.flowcontrol import Backoff
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import parse_iso
from kubernetes_tpu.utils.trace import SpanTracker, use_span

log = logging.getLogger("scheduler")

ASSUME_TTL = 30.0  # factory.go:100


class ConfigFactory:
    """Wires informers, cache, listers and builds a Scheduler."""

    def __init__(self, client: RESTClient,
                 scheduler_name: str = api.DEFAULT_SCHEDULER_NAME,
                 hard_pod_affinity_weight: int = 1,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)):
        self.client = client
        self.scheduler_name = scheduler_name
        self.cache = SchedulerCache(ttl=ASSUME_TTL)
        self.pending = FIFO()
        self.backoff = Backoff(initial=1.0, maximum=60.0)  # podBackoff
        # per-pending-pod spans: informer delivery -> queue wait -> bind,
        # correlated across the informer/batch/bind-pool threads
        self.spans = SpanTracker()
        # pods whose first delivery was already measured: retry deliveries
        # (our own Unschedulable status writes echoing back) must not
        # re-observe creation->delivery, which would fold scheduling and
        # backoff time into the watch-lag SLI
        self._delivered: set = set()
        self._informers = []

        # unassigned pods -> FIFO (spec.nodeName= ListWatch, factory.go:458-461)
        self.unassigned_informer = Informer(ListWatch(
            client, "pods",
            field_selector=fieldsel.parse_field_selector("spec.nodeName=")))
        self.unassigned_informer.add_event_handler(
            on_add=self._maybe_enqueue,
            on_update=lambda old, new: self._maybe_enqueue(new),
            on_delete=lambda p: self.pending.delete(p))

        # assigned pods -> scheduler cache (factory.go:126-137)
        self.assigned_informer = Informer(
            ListWatch(client, "pods",
                      field_selector=fieldsel.parse_field_selector("spec.nodeName!=")),
            indexers={"node": node_name_indexer})
        self.assigned_informer.add_event_handler(
            on_add=self.cache.add_pod,
            on_update=lambda old, new: self.cache.update_pod(new),
            on_delete=self.cache.remove_pod)

        # nodes -> cache + lister (factory.go:144-147)
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.node_informer.add_event_handler(
            on_add=self.cache.add_node,
            on_update=lambda old, new: self.cache.update_node(new),
            on_delete=self.cache.remove_node)

        self.service_informer = Informer(ListWatch(client, "services"))
        self.rc_informer = Informer(ListWatch(client, "replicationcontrollers"))
        self.rs_informer = Informer(ListWatch(client, "replicasets"))
        self.pv_informer = Informer(ListWatch(client, "persistentvolumes"))
        self.pvc_informer = Informer(ListWatch(client, "persistentvolumeclaims"))

        self._informers = [
            self.unassigned_informer, self.assigned_informer, self.node_informer,
            self.service_informer, self.rc_informer, self.rs_informer,
            self.pv_informer, self.pvc_informer,
        ]

        # copy_on_read=False: these run on the per-decision hot path (a
        # 30k-pod solve lists thousands of objects) and the scheduler only
        # READS them — deep-copies before any mutation (_with_node). The
        # checked-store test mode enforces that contract at test time.
        self.pod_lister = PodLister(self.assigned_informer.store,
                                    copy_on_read=False)
        self.node_lister = NodeLister(self.node_informer.store,
                                      copy_on_read=False)
        self.service_lister = ServiceLister(self.service_informer.store,
                                            copy_on_read=False)
        self.controller_lister = ControllerLister(self.rc_informer.store,
                                                  copy_on_read=False)
        self.replicaset_lister = ReplicaSetLister(self.rs_informer.store,
                                                  copy_on_read=False)

        self.plugin_args = PluginArgs(
            pod_lister=self.pod_lister,
            service_lister=self.service_lister,
            controller_lister=self.controller_lister,
            replicaset_lister=self.replicaset_lister,
            node_lookup=lambda name: self.node_informer.store.get(name),
            pvc_lookup=lambda ns, name: self.pvc_informer.store.get(f"{ns}/{name}"),
            pv_lookup=lambda name: self.pv_informer.store.get(name),
            hard_pod_affinity_weight=hard_pod_affinity_weight,
            failure_domains=tuple(failure_domains),
        )

    # --- dispatch filter (responsibleForPod, factory.go:426-432) -------------

    def _responsible_for(self, pod: api.Pod) -> bool:
        return api.get_pod_scheduler_name(pod) == self.scheduler_name

    def _maybe_enqueue(self, pod: api.Pod):
        if self._responsible_for(pod) and not (pod.spec and pod.spec.node_name):
            # span BEFORE the FIFO add: the scheduler loop may pop (and
            # close the queue_wait stage) the instant the pod is queued
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            if self.spans.current(key) is None:
                sp = self.spans.start(key, "schedule_pod", pod=key)
                created = parse_iso(pod.metadata.creation_timestamp)
                if created is not None and key not in self._delivered:
                    # creation -> FIRST informer delivery only (watch
                    # fan-out lag; the iso stamps are second-resolution, so
                    # this is coarse)
                    if len(self._delivered) > 200_000:
                        self._delivered.clear()
                    self._delivered.add(key)
                    # wall vs the serialized creationTimestamp
                    # kube-verify: disable-next-line=monotonic-duration
                    lag = max(time.time() - created, 0.0)
                    METRICS.observe("scheduler_informer_delivery_seconds", lag)
                    sp.attrs["informer_delivery_seconds"] = round(lag, 3)
            # if_idle: a watch echo for a pod mid-solve/bind must not
            # clobber its live stage with a bogus queue_wait
            self.spans.stage_if_idle(key, "queue_wait")
            self.pending.add(pod)

    # --- builders (CreateFromProvider/CreateFromConfig, factory.go:248-342) --

    def create_from_provider(self, provider_name: str = DEFAULT_PROVIDER,
                             algorithm_cls=GenericScheduler) -> "Scheduler":
        prov = get_provider(provider_name)
        predicates = get_predicates(prov["predicates"], self.plugin_args)
        priorities = get_priorities(prov["priorities"], self.plugin_args)
        return self._create(algorithm_cls(predicates, priorities))

    def create_from_policy(self, policy: dict,
                           algorithm_cls=GenericScheduler) -> "Scheduler":
        predicates, priorities, extender_cfgs = load_policy(policy, self.plugin_args)
        extenders = extenders_from_config(extender_cfgs)
        return self._create(algorithm_cls(predicates, priorities, extenders))

    def create_from_keys(self, predicate_keys, priority_keys,
                         algorithm_cls=GenericScheduler) -> "Scheduler":
        predicates = get_predicates(predicate_keys, self.plugin_args)
        priorities = get_priorities(priority_keys, self.plugin_args)
        return self._create(algorithm_cls(predicates, priorities))

    def _create(self, algorithm) -> "Scheduler":
        return Scheduler(self, algorithm)

    def create_batch_from_provider(self, provider_name: str = DEFAULT_PROVIDER,
                                   batch_size: int = 4096, weights=None,
                                   strict: bool = False,
                                   stage_deadlines=None, explain=None,
                                   objective=None, microbatch_ms: float = 0.0):
        """The TPU-backed batch scheduler (scheduler/tpu.py) with the oracle
        from the same provider as its device-failure fallback. `objective`
        selects a registered scheduling-objective mode
        (scheduler/objectives: binpack / preempt / gang / combinations);
        `microbatch_ms` > 0 accumulates arrivals for that window (or until
        batch_size) before each solve instead of solving per-pop."""
        from kubernetes_tpu.scheduler.tpu import create_batch_scheduler
        return create_batch_scheduler(self, provider_name,
                                      batch_size=batch_size, weights=weights,
                                      strict=strict,
                                      stage_deadlines=stage_deadlines,
                                      explain=explain, objective=objective,
                                      microbatch_ms=microbatch_ms)

    # --- lifecycle -----------------------------------------------------------

    def run(self, wait: bool = True, timeout: float = 10.0):
        for inf in self._informers:
            inf.run()
        if wait:
            for inf in self._informers:
                if not inf.wait_for_sync(timeout):
                    raise TimeoutError("informer failed to sync")
        return self

    def stop(self):
        self.pending.close()
        for inf in self._informers:
            inf.stop()


class _RequeueWorker:
    """ONE daemon delay-worker draining a heap of (due, seq, pod) — the
    backoff-requeue machinery for every failed pod.  The previous
    thread-per-failure scheme minted 30k threads for 30k unschedulable
    pods; this is bounded at one thread regardless of backlog.

    The heap is mutated only under the condition lock; the fire callback
    (a GET + FIFO re-add) runs with NO lock held."""

    def __init__(self, fire: Callable, stop: threading.Event):
        self._fire = fire
        self._stop = stop
        self._cv = threading.Condition()
        self._heap: list = []
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def add(self, delay: float, pod) -> None:
        with self._cv:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="scheduler-requeue", daemon=True)
                self._thread.start()
            heapq.heappush(self._heap,
                           (time.monotonic() + delay, self._seq, pod))
            self._seq += 1
            self._cv.notify()

    def wake(self) -> None:
        with self._cv:
            self._cv.notify()

    def __len__(self) -> int:
        with self._cv:
            return len(self._heap)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                if not self._heap:
                    self._cv.wait(0.5)
                    continue
                due = self._heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(min(due - now, 0.5))
                    continue
                _, _, pod = heapq.heappop(self._heap)
            try:
                self._fire(pod)
            except Exception:
                log.exception("requeue fire failed")


class Scheduler:
    """The loop (scheduler.go:89-155)."""

    def __init__(self, factory: ConfigFactory, algorithm):
        self.f = factory
        self.algorithm = algorithm
        self.recorder = EventRecorder(factory.client, factory.scheduler_name)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cleanup_thread: Optional[threading.Thread] = None
        self._requeue = _RequeueWorker(self._requeue_now, self._stop)
        # pod key -> score-breakdown text for the IN-FLIGHT bind, consumed
        # exactly once by _bind. Populated only by the kernel batch path for
        # the decision that produced this bind — a later fallback rebind
        # must not inherit a stale kernel record's provenance.
        self._bind_notes: dict = {}

    # --- one decision (scheduleOne, scheduler.go:93) -------------------------

    def schedule_one(self, timeout: Optional[float] = None) -> bool:
        """Pop one pending pod and (try to) schedule it. Returns False if the
        queue timed out / closed."""
        pod = self.f.pending.pop(timeout=timeout)
        if pod is None:
            return False
        self._schedule_pod(pod)
        return True

    def _note_popped(self, pod: api.Pod) -> None:
        """Close the pod's queue_wait span stage at FIFO pop, exporting the
        wait into the queue-wait SLI histogram."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        self.f.spans.end_stage(key, metric="scheduler_pod_queue_wait_seconds",
                               name="queue_wait")

    def _schedule_pod(self, pod: api.Pod) -> None:
        t_start = time.perf_counter()
        self._note_popped(pod)
        try:
            info = self.f.cache.get_node_name_to_info_map()
            nodes = self.f.node_lister.list()
            with METRICS.time("scheduler_scheduling_algorithm_latency_seconds"):
                dest = self.algorithm.schedule(pod, info, nodes)
        except Exception as e:  # FitError and scheduler bugs both requeue
            self._handle_failure(pod, e)
            return
        self._assume_and_bind(pod, dest, t_start)

    def _assume_and_bind(self, pod: api.Pod, dest: str, t_start: float) -> None:
        # optimistic assume before the async bind (scheduler.go:120-126)
        assumed = _with_node(pod, dest)
        try:
            self.f.cache.assume_pod(assumed)
            did_assume = True
        except ValueError:
            did_assume = False  # already cached (requeue race); bind anyway
        self._spawn_bind(pod, dest, t_start, did_assume)

    def _spawn_bind(self, pod, dest, t_start, did_assume):
        """Async bind dispatch; the batch scheduler overrides this with a
        bounded pool (one thread per pod is fine at 1 pod/iteration, not at
        4096)."""
        threading.Thread(target=self._bind, args=(pod, dest, t_start, did_assume),
                         daemon=True).start()

    def _bind(self, pod: api.Pod, dest: str, t_start: float, did_assume: bool):
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        bind_span = self.f.spans.stage(key, "bind", node=dest)
        binding = api.Binding(
            metadata=api.ObjectMeta(name=pod.metadata.name,
                                    namespace=pod.metadata.namespace),
            target=api.ObjectReference(kind="Node", name=dest))
        try:
            with METRICS.time("scheduler_binding_latency_seconds"):
                # the bind POST travels with the pod's trace: the apiserver
                # request span + audit record share this pod's trace id
                with use_span(bind_span):
                    self.f.client.bind(binding, pod.metadata.namespace)
        except Exception as e:
            # transport errors too — a dead bind thread with no rollback
            # would strand the pod booked-but-unbound until TTL expiry
            log.warning("binding failed for %s: %s", pod.metadata.name, e)
            # this decision's provenance dies with its bind: a later retry
            # is a NEW decision and must not inherit the note
            self._bind_notes.pop(key, None)
            if did_assume:
                # roll our own assume back; never evict informer-confirmed
                # state booked by an earlier successful bind
                self.f.cache.remove_pod(_with_node(pod, dest))
            self._handle_failure(pod, e)
            return
        METRICS.observe("scheduler_e2e_scheduling_latency_seconds",
                        time.perf_counter() - t_start)
        self.f.spans.finish(key)
        msg = f"Successfully assigned {pod.metadata.name} to {dest}"
        # decision provenance (kernel explain path): the score breakdown
        # rides the Scheduled event so `kubectl describe pod` can render a
        # Scheduling section without any new API surface
        note = self._bind_notes.pop(key, None)
        if note:
            msg += f" [{note}]"
        self.recorder.event(pod, "Normal", "Scheduled", msg)

    def _handle_failure(self, pod: api.Pod, err: Exception):
        """Error func: event + condition + backoff requeue
        (scheduler.go:102-107, factory.go:503-539)."""
        from kubernetes_tpu.observability.explain import note_unschedulable
        log.info("failed to schedule %s: %s", pod.metadata.name, err)
        root = self.f.spans.finish(
            f"{pod.metadata.namespace}/{pod.metadata.name}", error=str(err))
        # signature = the elimination histogram's shape (kernel decisions):
        # retries whose per-predicate counts drift with churn still dedup
        # onto ONE FailedScheduling Event instead of minting new objects
        self.recorder.event(pod, "Warning", "FailedScheduling", str(err),
                            signature=getattr(err, "signature", None))
        note_unschedulable(err)
        try:
            # status write under the pod's (just-finished) span: the audit
            # trail ties the Unschedulable PUT to the failed attempt's trace
            with use_span(root):
                self.f.client.request(
                    "PUT",
                    f"/api/v1/namespaces/{pod.metadata.namespace}/pods/{pod.metadata.name}/status",
                    _status_with_condition(pod, "Unschedulable", str(err)))
        except ApiError as e:
            # a pod whose Unschedulable verdict never lands looks healthy to
            # every API consumer — this failure must be visible
            log.warning("Unschedulable status write failed for %s/%s: %s",
                        pod.metadata.namespace, pod.metadata.name, e)
            METRICS.inc("scheduler_status_write_errors_total")
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        self._requeue.add(self.f.backoff.next(key), pod)

    def _requeue_now(self, pod: api.Pod) -> None:
        """Delay-worker fire: refetch and re-queue if still unassigned."""
        if self._stop.is_set():
            return
        try:
            fresh = self.f.client.get("pods", pod.metadata.name,
                                      pod.metadata.namespace)
        except ApiError:
            return  # deleted meanwhile
        if not (fresh.spec and fresh.spec.node_name):
            self.f.pending.add_if_not_present(fresh)

    # --- loop ----------------------------------------------------------------

    def run(self):
        self._thread = threading.Thread(target=self._loop, name="scheduler",
                                        daemon=True)
        self._thread.start()
        self._cleanup_thread = threading.Thread(target=self._cleanup_loop,
                                                name="scheduler-cache-gc",
                                                daemon=True)
        self._cleanup_thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.schedule_one(timeout=0.5)
            except Exception:
                log.exception("scheduleOne crashed")  # HandleCrash

    def _cleanup_loop(self):
        while not self._stop.wait(1.0):
            self.f.cache.cleanup_expired()

    def stop(self):
        self._stop.set()
        self._requeue.wake()
        if self._thread:
            self._thread.join(timeout=5)


def _with_node(pod: api.Pod, node_name: str) -> api.Pod:
    from kubernetes_tpu.api.serialization import deep_copy
    p = deep_copy(pod)
    p.spec.node_name = node_name
    return p


def _status_with_condition(pod: api.Pod, reason: str, message: str) -> dict:
    from kubernetes_tpu.api.serialization import scheme, deep_copy
    p = deep_copy(pod)
    if p.status is None:
        p.status = api.PodStatus()
    set_pod_condition(p, api.POD_SCHEDULED, api.CONDITION_FALSE, reason, message)
    # don't carry a stale rv into the status CAS precondition
    p.metadata.resource_version = ""
    return scheme.encode(p)
