"""Batch scheduling backends: the TPU kernel and the sequential oracle.

The TPU backend (BASELINE.json north star) schedules a whole pending-pod
batch in one device program; the oracle runs the reference-semantics
sequential loop (generic.py) over the same inputs and is the ground truth the
kernel must match binding-for-binding (SURVEY §7 "what done means").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.ops.kernel import Weights, schedule_batch
from kubernetes_tpu.ops.tensorize import Tensorizer
from kubernetes_tpu.scheduler.cache import NodeInfo
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler
from kubernetes_tpu.scheduler.provider import PluginArgs, get_predicates, get_priorities


DEFAULT_PREDICATE_KEYS = [
    "NoDiskConflict", "NoVolumeZoneConflict", "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount", "GeneralPredicates", "PodToleratesNodeTaints",
    "CheckNodeMemoryPressure", "MatchInterPodAffinity",
]
DEFAULT_PRIORITY_KEYS = [
    "LeastRequestedPriority", "BalancedResourceAllocation",
    "SelectorSpreadPriority", "NodeAffinityPriority", "TaintTolerationPriority",
    "InterPodAffinityPriority",
]


class ListPodLister:
    """Pod lister over a mutable list (committed pods get appended, so
    predicates see in-batch assumes like the real cache-backed lister)."""

    def __init__(self, pods: Optional[List[api.Pod]] = None):
        self.pods = list(pods or [])

    def list(self, selector=None):
        if selector is None:
            return list(self.pods)
        return [p for p in self.pods
                if selector.matches((p.metadata.labels or {}))]


class ListServiceLister:
    def __init__(self, services: Sequence[api.Service] = ()):
        self.services = list(services)

    def get_pod_services(self, pod):
        out = []
        lbls = (pod.metadata.labels or {})
        for svc in self.services:
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector if svc.spec else None
            if sel and labelsel.selector_from_map(sel).matches(lbls):
                out.append(svc)
        return out


class EmptyLister:
    def get_pod_controllers(self, pod):
        return []

    def get_pod_replica_sets(self, pod):
        return []

    def get_pod_services(self, pod):
        return []

    def list(self, selector=None):
        return []


def make_plugin_args(nodes: List[api.Node], pod_lister=None,
                     service_lister=None, controller_lister=None,
                     replicaset_lister=None, pvc_lookup=None,
                     pv_lookup=None) -> PluginArgs:
    node_map = {n.metadata.name: n for n in nodes}
    empty = EmptyLister()
    return PluginArgs(
        pod_lister=pod_lister or ListPodLister(),
        service_lister=service_lister or empty,
        controller_lister=controller_lister or empty,
        replicaset_lister=replicaset_lister or empty,
        node_lookup=node_map.get,
        pvc_lookup=pvc_lookup,
        pv_lookup=pv_lookup,
    )


def oracle_batch(nodes: List[api.Node], existing: List[api.Pod],
                 pending: List[api.Pod], args: PluginArgs,
                 predicate_keys=None, priority_keys=None) -> List[Optional[str]]:
    """Sequential reference loop: schedule each pod in FIFO order, assuming
    each placement into the world model before the next (scheduler.go:93 +
    cache.go:101 semantics)."""
    predicates = get_predicates(predicate_keys or DEFAULT_PREDICATE_KEYS, args)
    priorities = get_priorities(priority_keys or DEFAULT_PRIORITY_KEYS, args)
    sched = GenericScheduler(predicates, priorities, parallel=False)

    info: Dict[str, NodeInfo] = {n.metadata.name: NodeInfo(n) for n in nodes}
    for ep in existing:
        name = ep.spec.node_name if ep.spec else ""
        if name in info:
            info[name].add_pod(ep)

    out: List[Optional[str]] = []
    for pod in pending:
        try:
            host = sched.schedule(pod, info, nodes)
        except FitError:
            out.append(None)
            continue
        out.append(host)
        committed = deep_copy(pod)
        committed.spec.node_name = host
        info[host].add_pod(committed)
        if isinstance(args.pod_lister, ListPodLister):
            args.pod_lister.pods.append(committed)
    return out


def tpu_batch(nodes: List[api.Node], existing: List[api.Pod],
              pending: List[api.Pod], args: PluginArgs,
              weights: Optional[Weights] = None,
              stage=None, explain: bool = False, objective=None):
    """The TPU path: tensorize + device kernel. `stage(name, fn)` is the
    watchdog/span hook (ops/watchdog.run_stages) naming the pipeline stages
    tensorize -> upload -> compile|solve. With explain, returns
    (names, DecisionRecords) — per-predicate provenance straight from the
    solve (observability/explain.py). With an enabled objective
    (name or ObjectiveConfig — scheduler/objectives), the return grows an
    ObjectiveOutcome: (names, outcome) / (names, records, outcome)."""
    from kubernetes_tpu.scheduler.objectives.config import (
        gang_order, resolve_objective,
    )
    objective = resolve_objective(objective)
    perm = None
    if objective is not None and objective.gang:
        # gang members must be contiguous in scan order; solve in the
        # gang-grouped order and un-permute the names below
        pending, perm = gang_order(pending)
    run = stage or (lambda _n, fn: fn())
    ct = run("tensorize",
             lambda: Tensorizer(plugin_args=args,
                                objective=objective).build(nodes, existing,
                                                           pending))
    ret = schedule_batch(ct, weights, stage=stage, explain=explain,
                         objective=objective)
    if perm is None:
        return ret
    from kubernetes_tpu.ops.kernel import unpermute_result
    return unpermute_result(ret, perm)
