"""Algorithm provider registry + policy file API.

Parity target: reference plugin/pkg/scheduler/factory/plugins.go (the
RegisterFitPredicate / RegisterPriorityConfigFactory / RegisterAlgorithmProvider
registry), algorithmprovider/defaults/defaults.go:55-197 (DefaultProvider
contents), and the versioned policy-file API
(plugin/pkg/scheduler/api/types.go:27-173) loaded via --policy-config-file
with its restricted custom predicate/priority argument forms
(ServiceAffinity/LabelsPresence and ServiceAntiAffinity/LabelPreference).

Factories take a PluginArgs carrying the listers the plugin needs, so
registration order is decoupled from informer wiring (the reference's
PluginFactoryArgs pattern).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler import predicates as preds
from kubernetes_tpu.scheduler import priorities as prios
from kubernetes_tpu.scheduler.generic import PriorityConfig
# the scheduling-objective registry rides the same provider boundary
# (ROADMAP 5's pluggable-objective seam): objectives register by name,
# providers/policies select them by name, unknown names raise KeyError
from kubernetes_tpu.scheduler.objectives.config import (  # noqa: F401
    ObjectiveConfig, get_objective, objective_names, register_objective,
    resolve_objective,
)


@dataclass
class PluginArgs:
    """What plugin factories may depend on (PluginFactoryArgs)."""

    pod_lister: object = None
    service_lister: object = None
    controller_lister: object = None
    replicaset_lister: object = None
    node_lookup: Callable = None           # name -> Node
    pvc_lookup: Callable = None            # (ns, name) -> PVC
    pv_lookup: Callable = None             # name -> PV
    hard_pod_affinity_weight: int = 1
    failure_domains: tuple = (api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)


_PREDICATE_FACTORIES: Dict[str, Callable] = {}
_PRIORITY_FACTORIES: Dict[str, Callable] = {}  # name -> (args) -> PriorityConfig
_PROVIDERS: Dict[str, dict] = {}


def register_fit_predicate(name: str, factory: Callable):
    _PREDICATE_FACTORIES[name] = factory
    return name


def register_priority(name: str, weight: int, factory: Callable):
    def mk(args: PluginArgs, w: int = weight) -> PriorityConfig:
        return PriorityConfig(factory(args), weight=w, name=name)

    _PRIORITY_FACTORIES[name] = mk
    return name


def register_algorithm_provider(name: str, predicate_keys: List[str],
                                priority_keys: List[str],
                                objective: Optional[str] = None):
    """Register a provider; `objective` (optional) names a registered
    scheduling objective the provider's batch scheduler solves under —
    validated eagerly so a typo fails at registration, not at solve time."""
    if objective is not None:
        get_objective(objective)  # KeyError on unknown names
    _PROVIDERS[name] = {"predicates": list(predicate_keys),
                        "priorities": list(priority_keys),
                        "objective": objective}
    return name


def get_predicates(keys: List[str], args: PluginArgs) -> Dict[str, Callable]:
    out = {}
    for k in keys:
        if k not in _PREDICATE_FACTORIES:
            raise KeyError(f"unknown fit predicate {k!r}")
        out[k] = _PREDICATE_FACTORIES[k](args)
    return out


def get_priorities(keys: List[str], args: PluginArgs,
                   weights: Optional[Dict[str, int]] = None) -> List[PriorityConfig]:
    out = []
    for k in keys:
        if k not in _PRIORITY_FACTORIES:
            raise KeyError(f"unknown priority {k!r}")
        cfg = _PRIORITY_FACTORIES[k](args)
        if weights and k in weights:
            cfg.weight = weights[k]
        out.append(cfg)
    return out


def get_provider(name: str) -> dict:
    if name not in _PROVIDERS:
        raise KeyError(f"unknown algorithm provider {name!r}")
    return _PROVIDERS[name]


# --- built-in registrations (defaults.go:55-197) ------------------------------

register_fit_predicate("PodFitsResources", lambda a: preds.pod_fits_resources)
register_fit_predicate("PodFitsHost", lambda a: preds.pod_fits_host)
register_fit_predicate("PodFitsHostPorts", lambda a: preds.pod_fits_host_ports)
register_fit_predicate("MatchNodeSelector", lambda a: preds.pod_matches_node_selector)
register_fit_predicate("GeneralPredicates", lambda a: preds.general_predicates)
register_fit_predicate("NoDiskConflict", lambda a: preds.no_disk_conflict)
register_fit_predicate(
    "MaxEBSVolumeCount",
    lambda a: preds.MaxPDVolumeCountChecker(
        "ebs", preds.DEFAULT_MAX_EBS_VOLUMES, a.pvc_lookup, a.pv_lookup))
register_fit_predicate(
    "MaxGCEPDVolumeCount",
    lambda a: preds.MaxPDVolumeCountChecker(
        "gce-pd", preds.DEFAULT_MAX_GCE_PD_VOLUMES, a.pvc_lookup, a.pv_lookup))
register_fit_predicate(
    "NoVolumeZoneConflict",
    lambda a: (preds.VolumeZoneChecker(a.pvc_lookup, a.pv_lookup)
               if a.pvc_lookup and a.pv_lookup else _noop_predicate))
register_fit_predicate("PodToleratesNodeTaints",
                       lambda a: preds.pod_tolerates_node_taints)
register_fit_predicate("CheckNodeMemoryPressure",
                       lambda a: preds.check_node_memory_pressure)
register_fit_predicate(
    "MatchInterPodAffinity",
    lambda a: preds.InterPodAffinity(a.pod_lister, a.node_lookup,
                                     a.failure_domains))

register_priority("LeastRequestedPriority", 1, lambda a: prios.least_requested)
register_priority("BalancedResourceAllocation", 1,
                  lambda a: prios.balanced_resource_allocation)
register_priority("SelectorSpreadPriority", 1,
                  lambda a: prios.SelectorSpread(a.service_lister,
                                                 a.controller_lister,
                                                 a.replicaset_lister))
register_priority("NodeAffinityPriority", 1, lambda a: prios.node_affinity_priority)
register_priority("TaintTolerationPriority", 1,
                  lambda a: prios.taint_toleration_priority)
register_priority(
    "InterPodAffinityPriority", 1,
    lambda a: prios.InterPodAffinityPriority(a.pod_lister, a.node_lookup,
                                             a.hard_pod_affinity_weight,
                                             a.failure_domains))
register_priority("ImageLocalityPriority", 1,
                  lambda a: prios.image_locality_priority)
register_priority("EqualPriority", 1, lambda a: prios.equal_priority)
# MostRequested: the binpack objective's sequential reference — registered
# so the oracle (and the BatchScheduler's sequential fallback) can run the
# same fragmentation-minimizing scoring the kernel's binpack mode traces
register_priority("MostRequestedPriority", 1, lambda a: prios.most_requested)


def _noop_predicate(pod, node_info):
    return None


DEFAULT_PROVIDER = register_algorithm_provider(
    "DefaultProvider",
    # defaults.go:110-143
    ["NoDiskConflict", "NoVolumeZoneConflict", "MaxEBSVolumeCount",
     "MaxGCEPDVolumeCount", "GeneralPredicates", "PodToleratesNodeTaints",
     "CheckNodeMemoryPressure", "MatchInterPodAffinity"],
    ["LeastRequestedPriority", "BalancedResourceAllocation",
     "SelectorSpreadPriority", "NodeAffinityPriority",
     "TaintTolerationPriority", "InterPodAffinityPriority"],
)


# --- policy file (api/types.go:27-173) ---------------------------------------

def policy_objective(policy: dict) -> Optional[ObjectiveConfig]:
    """Resolve a policy dict's `objective` key (name of a registered
    scheduling objective) to its config; None when the policy names none.
    Unknown names raise KeyError — a policy typo must fail loudly, exactly
    like an unknown predicate/priority name."""
    name = policy.get("objective")
    return get_objective(name) if name is not None else None


def load_policy(policy: dict, args: PluginArgs):
    """Build (predicates, priorities, extender_configs) from a policy dict
    (the --policy-config-file JSON). Custom predicate arguments are limited
    to ServiceAffinity/LabelsPresence; custom priorities to
    ServiceAntiAffinity/LabelPreference — exactly the reference's whitelist.
    An `objective` key is validated against the objective registry here
    (consumed by the batch scheduler via policy_objective)."""
    policy_objective(policy)  # validate eagerly: unknown names fail the load
    predicates: Dict[str, Callable] = {}
    for p in policy.get("predicates", []):
        name, argspec = p["name"], p.get("argument")
        if argspec and "serviceAffinity" in argspec:
            predicates[name] = preds.ServiceAffinity(
                args.pod_lister, args.service_lister, args.node_lookup,
                argspec["serviceAffinity"]["labels"])
        elif argspec and "labelsPresence" in argspec:
            predicates[name] = preds.NodeLabelChecker(
                argspec["labelsPresence"]["labels"],
                argspec["labelsPresence"].get("presence", True))
        else:
            predicates.update(get_predicates([name], args))
    priorities: List[PriorityConfig] = []
    for p in policy.get("priorities", []):
        name, weight = p["name"], p.get("weight", 1)
        argspec = p.get("argument")
        if argspec and "serviceAntiAffinity" in argspec:
            priorities.append(PriorityConfig(
                prios.ServiceAntiAffinity(args.pod_lister, args.service_lister,
                                          argspec["serviceAntiAffinity"]["label"]),
                weight=weight, name=name))
        elif argspec and "labelPreference" in argspec:
            priorities.append(PriorityConfig(
                prios.NodeLabelPriority(
                    argspec["labelPreference"]["label"],
                    argspec["labelPreference"].get("presence", True)),
                weight=weight, name=name))
        else:
            priorities.extend(get_priorities([name], args, weights={name: weight}))
    return predicates, priorities, policy.get("extenders", [])


def load_policy_file(path: str, args: PluginArgs):
    with open(path) as f:
        return load_policy(json.load(f), args)
