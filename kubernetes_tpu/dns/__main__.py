"""kube-dns entrypoint (reference cmd/kube-dns/dns.go flag surface subset)."""

import argparse
import logging
import signal
import threading

from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.dns.server import DNSServer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kube-dns")
    ap.add_argument("--kube-master", default="127.0.0.1:8080",
                    help="host:port of the API server")
    ap.add_argument("--dns-port", type=int, default=10053)
    ap.add_argument("--dns-bind", default="127.0.0.1")
    ap.add_argument("--domain", default="cluster.local")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    master = args.kube_master
    if "//" in master:
        master = master.split("//", 1)[1]
    host, _, port = master.rstrip("/").partition(":")
    client = RESTClient(host=host, port=int(port or 8080))
    server = DNSServer(client, domain=args.domain, port=args.dns_port,
                       host=args.dns_bind).start()
    # parseable banner on stdout (localup reads it to learn the bound
    # port when started with --dns-port 0, like the apiserver's banner)
    print(f"dns listening on {args.dns_bind}:{server.port}", flush=True)
    logging.info("kube-dns serving %s on %s:%d", args.domain, args.dns_bind,
                 server.port)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    try:
        done.wait()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
