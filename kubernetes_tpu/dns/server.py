"""Cluster DNS over the service/endpoints informers.

Record forms (reference cmd/kube-dns/dns.go; skydns path conventions):

  {svc}.{ns}.svc.{domain}                  A -> clusterIP, or one A per
                                                ready endpoint address when
                                                the service is headless
                                                (clusterIP == "None")
  {host}.{svc}.{ns}.svc.{domain}           A -> that endpoint (headless):
                                                `host` is the address
                                                hostname (target pod name)
                                                or the dashed IP (10-0-0-3)
  _{port}._{proto}.{svc}.{ns}.svc.{domain} SRV -> service port; one record
                                                per endpoint for headless
  {reversed}.in-addr.arpa                  PTR -> {svc}.{ns}.svc.{domain}
                                                for allocated cluster IPs

Nonexistent names inside the cluster domain answer NXDOMAIN; names outside
it REFUSED (this server is authoritative only — no recursion, matching the
reference's skydns `no_rec` deployment mode). AAAA for an existing name
answers NOERROR with zero answers so v6-preferring resolvers fall through
to A.

The UDP responder is a single thread on a datagram socket; each query is
answered from the informer stores' current state — no record cache to
invalidate, the watch IS the cache coherence protocol.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient

log = logging.getLogger("kubedns")

# qtypes
TYPE_A = 1
TYPE_PTR = 12
TYPE_AAAA = 28
TYPE_SRV = 33
TYPE_ANY = 255
CLASS_IN = 1

# rcodes
RCODE_OK = 0
RCODE_NXDOMAIN = 3
RCODE_REFUSED = 5


# --- wire codec (RFC 1035) ----------------------------------------------------

def _encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        raw = label.encode("ascii")
        if not 0 < len(raw) < 64:
            raise ValueError(f"bad label {label!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def _read_name(buf: bytes, off: int) -> Tuple[str, int]:
    """Decode a (possibly compressed) name; returns (name, next offset)."""
    labels: List[str] = []
    jumped = False
    end = off
    hops = 0
    while True:
        if off >= len(buf):
            raise ValueError("truncated name")
        ln = buf[off]
        if ln & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(buf):
                raise ValueError("truncated pointer")
            ptr = ((ln & 0x3F) << 8) | buf[off + 1]
            if not jumped:
                end = off + 2
            off = ptr
            jumped = True
            hops += 1
            if hops > 32:
                raise ValueError("pointer loop")
            continue
        off += 1
        if ln == 0:
            if not jumped:
                end = off
            break
        labels.append(buf[off:off + ln].decode("ascii", "replace"))
        off += ln
    return ".".join(labels), end


def encode_query(qid: int, name: str, qtype: int) -> bytes:
    """Client-side query encoder (used by tests and the resolver helper)."""
    hdr = struct.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0)  # RD set
    return hdr + _encode_name(name) + struct.pack(">HH", qtype, CLASS_IN)


def decode_response(data: bytes) -> dict:
    """Minimal response decoder: {'id', 'rcode', 'answers': [(name, type,
    rdata)]} where rdata is a dotted IP for A, a name for PTR, and
    (prio, weight, port, target) for SRV."""
    qid, flags, qd, an, _, _ = struct.unpack(">HHHHHH", data[:12])
    off = 12
    for _ in range(qd):
        _, off = _read_name(data, off)
        off += 4
    answers = []
    for _ in range(an):
        name, off = _read_name(data, off)
        rtype, _, _, rdlen = struct.unpack(">HHIH", data[off:off + 10])
        off += 10
        rdata = data[off:off + rdlen]
        if rtype == TYPE_A:
            answers.append((name, rtype, socket.inet_ntoa(rdata)))
        elif rtype == TYPE_PTR:
            target, _ = _read_name(data, off)
            answers.append((name, rtype, target))
        elif rtype == TYPE_SRV:
            prio, weight, port = struct.unpack(">HHH", rdata[:6])
            target, _ = _read_name(data, off + 6)
            answers.append((name, rtype, (prio, weight, port, target)))
        else:
            answers.append((name, rtype, rdata))
        off += rdlen
    return {"id": qid, "rcode": flags & 0xF, "answers": answers}


def _rr(name: str, rtype: int, rdata: bytes, ttl: int = 30) -> bytes:
    return (_encode_name(name) + struct.pack(">HHIH", rtype, CLASS_IN, ttl,
                                             len(rdata)) + rdata)


# --- the server ---------------------------------------------------------------

class DNSServer:
    """Authoritative DNS for `svc.{domain}` off the cluster watch."""

    def __init__(self, client: Optional[RESTClient] = None,
                 domain: str = "cluster.local", port: int = 0,
                 host: str = "127.0.0.1"):
        self.domain = domain.strip(".")
        self._host = host
        self._port = port
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.svc_informer = self.ep_informer = None
        if client is not None:
            self.svc_informer = Informer(ListWatch(client, "services"))
            self.ep_informer = Informer(ListWatch(client, "endpoints"))
        # static tables for informer-less (unit) use
        self._services: Dict[Tuple[str, str], api.Service] = {}
        self._endpoints: Dict[Tuple[str, str], api.Endpoints] = {}

    # -- state feeding ---------------------------------------------------------

    def set_static(self, services: List[api.Service],
                   endpoints: List[api.Endpoints]) -> None:
        self._services = {(s.metadata.namespace or "default",
                           s.metadata.name): s for s in services}
        self._endpoints = {(e.metadata.namespace or "default",
                            e.metadata.name): e for e in endpoints}

    def _service(self, ns: str, name: str) -> Optional[api.Service]:
        if self.svc_informer is not None:
            # keyed O(1) lookup (ThreadSafeStore ns/name keys) — the
            # responder is single-threaded; per-packet linear scans would
            # make DNS latency scale with cluster size
            store = self.svc_informer.store
            return store.get(f"{ns}/{name}") or store.get(name)
        return self._services.get((ns, name))

    def _eps(self, ns: str, name: str) -> Optional[api.Endpoints]:
        if self.ep_informer is not None:
            store = self.ep_informer.store
            return store.get(f"{ns}/{name}") or store.get(name)
        return self._endpoints.get((ns, name))

    def _all_services(self):
        if self.svc_informer is not None:
            return list(self.svc_informer.store.list())
        return list(self._services.values())

    # -- lifecycle -------------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._sock is not None, "server not started"
        return self._sock.getsockname()[1]

    def start(self) -> "DNSServer":
        if self.svc_informer is not None:
            self.svc_informer.run()
            self.ep_informer.run()
            self.svc_informer.wait_for_sync(30)
            self.ep_informer.wait_for_sync(30)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((self._host, self._port))
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="kube-dns", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        for inf in (self.svc_informer, self.ep_informer):
            if inf is not None:
                inf.stop()

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, addr = self._sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                resp = self.handle(data)
            except Exception:  # a bad packet must not kill the server
                log.exception("dns: dropping malformed query")
                continue
            if resp is not None:
                try:
                    self._sock.sendto(resp, addr)
                except OSError:
                    pass

    # -- resolution ------------------------------------------------------------

    def handle(self, data: bytes) -> Optional[bytes]:
        if len(data) < 12:
            return None
        qid, flags, qd, _, _, _ = struct.unpack(">HHHHHH", data[:12])
        if flags & 0x8000 or qd < 1:  # response bit set / no question
            return None
        off = 12
        qname, off = _read_name(data, off)
        qtype, qclass = struct.unpack(">HH", data[off:off + 4])
        question = (_encode_name(qname)
                    + struct.pack(">HH", qtype, qclass))
        if qclass != CLASS_IN:
            return self._reply(qid, question, RCODE_REFUSED, [])
        rcode, answers = self.resolve(qname.lower(), qtype)
        return self._reply(qid, question, rcode, answers)

    @staticmethod
    def _reply(qid: int, question: bytes, rcode: int,
               answers: List[bytes]) -> bytes:
        flags = 0x8400 | rcode  # QR + AA
        hdr = struct.pack(">HHHHHH", qid, flags, 1, len(answers), 0, 0)
        return hdr + question + b"".join(answers)

    def resolve(self, qname: str, qtype: int) -> Tuple[int, List[bytes]]:
        """(rcode, encoded answer RRs) for one question."""
        if qname.endswith(".in-addr.arpa"):
            return self._resolve_ptr(qname, qtype)
        suffix = f".svc.{self.domain}"
        if not qname.endswith(suffix):
            # not ours: REFUSED unless it's the bare domain
            return ((RCODE_NXDOMAIN, []) if qname.endswith(self.domain)
                    else (RCODE_REFUSED, []))
        rel = qname[: -len(suffix)]
        parts = rel.split(".")
        if len(parts) == 2:
            svc, eps = self._lookup(parts[1], parts[0])
            if svc is None:
                return RCODE_NXDOMAIN, []
            if qtype in (TYPE_A, TYPE_ANY):
                return RCODE_OK, self._a_records(qname, svc, eps)
            return RCODE_OK, []  # AAAA etc on an existing name: empty NOERROR
        if len(parts) == 3 and not parts[0].startswith("_"):
            # {host}.{svc}.{ns}: headless per-endpoint record
            svc, eps = self._lookup(parts[2], parts[1])
            if svc is None or not _headless(svc):
                return RCODE_NXDOMAIN, []
            ips = [ip for host, ip in _endpoint_hosts(eps)
                   if host == parts[0]]
            if not ips:
                return RCODE_NXDOMAIN, []
            if qtype in (TYPE_A, TYPE_ANY):
                return RCODE_OK, [
                    _rr(qname, TYPE_A, socket.inet_aton(ip)) for ip in ips]
            return RCODE_OK, []
        if len(parts) == 4 and parts[0].startswith("_") \
                and parts[1].startswith("_"):
            return self._resolve_srv(qname, parts)
        return RCODE_NXDOMAIN, []

    def _lookup(self, ns: str, name: str):
        svc = self._service(ns, name)
        eps = self._eps(ns, name) if svc is not None else None
        return svc, eps

    def _a_records(self, qname: str, svc: api.Service,
                   eps: Optional[api.Endpoints]) -> List[bytes]:
        if _headless(svc):
            return [_rr(qname, TYPE_A, socket.inet_aton(ip))
                    for _, ip in _endpoint_hosts(eps)]
        ip = svc.spec.cluster_ip if svc.spec else ""
        if not ip or ip == "None":
            return []
        return [_rr(qname, TYPE_A, socket.inet_aton(ip))]

    def _resolve_srv(self, qname: str, parts: List[str]):
        portname, proto = parts[0][1:], parts[1][1:]
        svc, eps = self._lookup(parts[3], parts[2])
        if svc is None or svc.spec is None:
            return RCODE_NXDOMAIN, []
        matching = [p for p in (svc.spec.ports or [])
                    if (p.protocol or "TCP").lower() == proto
                    and (p.name or "") == portname]
        if not matching:
            return RCODE_NXDOMAIN, []
        svc_name = f"{svc.metadata.name}.{svc.metadata.namespace or 'default'}" \
                   f".svc.{self.domain}"
        out = []
        for p in matching:
            if _headless(svc):
                for host, _ in _endpoint_hosts(eps):
                    target = f"{host}.{svc_name}"
                    out.append(_rr(qname, TYPE_SRV,
                                   struct.pack(">HHH", 10, 10, p.port)
                                   + _encode_name(target)))
            else:
                out.append(_rr(qname, TYPE_SRV,
                               struct.pack(">HHH", 10, 10, p.port)
                               + _encode_name(svc_name)))
        return RCODE_OK, out

    def _resolve_ptr(self, qname: str, qtype: int):
        if qtype not in (TYPE_PTR, TYPE_ANY):
            return RCODE_OK, []
        octets = qname[: -len(".in-addr.arpa")].split(".")
        if len(octets) != 4:
            return RCODE_NXDOMAIN, []
        ip = ".".join(reversed(octets))
        for s in self._all_services():
            if s.spec and s.spec.cluster_ip == ip:
                target = (f"{s.metadata.name}."
                          f"{s.metadata.namespace or 'default'}"
                          f".svc.{self.domain}")
                return RCODE_OK, [_rr(qname, TYPE_PTR, _encode_name(target))]
        return RCODE_NXDOMAIN, []


def _headless(svc: api.Service) -> bool:
    return bool(svc.spec) and svc.spec.cluster_ip == "None"


def _endpoint_hosts(eps: Optional[api.Endpoints]) -> List[Tuple[str, str]]:
    """(host-label, ip) per ready endpoint address: the target pod name when
    the endpoints controller recorded one, else the dashed IP."""
    out = []
    for ss in (eps.subsets or []) if eps else []:
        for a in ss.addresses or []:
            if not a.ip:
                continue
            host = (a.target_ref.name if a.target_ref and a.target_ref.name
                    else a.ip.replace(".", "-"))
            out.append((host, a.ip))
    return out


def resolve_udp(port: int, name: str, qtype: int = TYPE_A,
                host: str = "127.0.0.1", timeout: float = 2.0) -> dict:
    """One-shot client over a real UDP socket (tests + debugging)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(encode_query(0x1234, name, qtype), (host, port))
        data, _ = s.recvfrom(4096)
    finally:
        s.close()
    return decode_response(data)
