"""Cluster DNS (kube-dns analog).

Parity target: reference cmd/kube-dns/dns.go — skydns backed by the
service/endpoints watch. Here the record table is computed straight off the
service + endpoints informer stores and served by a small RFC-1035 UDP
responder; no external DNS library, no intermediate etcd.
"""

from kubernetes_tpu.dns.server import DNSServer, encode_query, decode_response

__all__ = ["DNSServer", "encode_query", "decode_response"]
