"""batch/v1 (Job) and batch/v2alpha1 (ScheduledJob) groups.

Parity target: reference pkg/apis/batch/types.go — JobSpec with
parallelism/completions/activeDeadlineSeconds, JobCondition Complete/Failed,
ScheduledJob with cron schedule + concurrency policy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import (
    LabelSelector, ObjectMeta, ObjectReference, PodTemplateSpec,
)

GROUP_VERSION = "batch/v1"
GROUP_VERSION_V2 = "batch/v2alpha1"

JOB_COMPLETE = "Complete"
JOB_FAILED = "Failed"

# ConcurrencyPolicy (reference batch/types.go)
ALLOW_CONCURRENT = "Allow"
FORBID_CONCURRENT = "Forbid"
REPLACE_CONCURRENT = "Replace"


@dataclass
class JobSpec:
    parallelism: Optional[int] = None
    completions: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    selector: Optional[LabelSelector] = None
    manual_selector: Optional[bool] = None
    template: Optional[PodTemplateSpec] = None


@dataclass
class JobCondition:
    type: str = ""      # Complete | Failed
    status: str = ""    # True | False | Unknown
    last_probe_time: Optional[str] = None
    last_transition_time: Optional[str] = None
    reason: str = ""
    message: str = ""


@dataclass
class JobStatus:
    conditions: Optional[List[JobCondition]] = None
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class Job:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[JobSpec] = None
    status: Optional[JobStatus] = None


@dataclass
class JobTemplateSpec:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[JobSpec] = None


@dataclass
class ScheduledJobSpec:
    schedule: str = ""  # cron format
    starting_deadline_seconds: Optional[int] = None
    concurrency_policy: str = ALLOW_CONCURRENT
    suspend: Optional[bool] = None
    job_template: Optional[JobTemplateSpec] = None


@dataclass
class ScheduledJobStatus:
    active: Optional[List[ObjectReference]] = None
    last_schedule_time: Optional[str] = None


@dataclass
class ScheduledJob:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ScheduledJobSpec] = None
    status: Optional[ScheduledJobStatus] = None


scheme.add_known_type(GROUP_VERSION, "Job", Job)
scheme.add_known_type(GROUP_VERSION_V2, "ScheduledJob", ScheduledJob)
