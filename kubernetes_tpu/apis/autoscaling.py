"""autoscaling/v1 group.

Parity target: reference pkg/apis/autoscaling/types.go —
HorizontalPodAutoscaler keyed on target CPU utilization percentage, scaling a
CrossVersionObjectReference target through its scale subresource.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import ObjectMeta

GROUP_VERSION = "autoscaling/v1"


@dataclass
class CrossVersionObjectReference:
    kind: str = ""
    name: str = ""
    api_version: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: Optional[CrossVersionObjectReference] = None
    min_replicas: Optional[int] = None
    max_replicas: int = 0
    target_cpu_utilization_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscalerStatus:
    observed_generation: Optional[int] = None
    last_scale_time: Optional[str] = None
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscaler:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[HorizontalPodAutoscalerSpec] = None
    status: Optional[HorizontalPodAutoscalerStatus] = None


scheme.add_known_type(GROUP_VERSION, "HorizontalPodAutoscaler",
                      HorizontalPodAutoscaler)
