"""apps/v1alpha1 group.

Parity target: reference pkg/apis/apps/types.go — PetSet (the ancestor of
StatefulSet): ordered, identity-preserving replicas with per-pet volume
claims and a governing service.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import (
    LabelSelector, ObjectMeta, PersistentVolumeClaim, PodTemplateSpec,
)

GROUP_VERSION = "apps/v1alpha1"


@dataclass
class PetSetSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    volume_claim_templates: Optional[List[PersistentVolumeClaim]] = None
    service_name: str = ""


@dataclass
class PetSetStatus:
    observed_generation: Optional[int] = None
    replicas: int = 0


@dataclass
class PetSet:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PetSetSpec] = None
    status: Optional[PetSetStatus] = None


scheme.add_known_type(GROUP_VERSION, "PetSet", PetSet)
