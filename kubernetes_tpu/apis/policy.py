"""policy/v1alpha1 group.

Parity target: reference pkg/apis/policy/types.go — PodDisruptionBudget:
minAvailable (int or percent) over a label-selected pod set; status says
whether a voluntary disruption is currently allowed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import LabelSelector, ObjectMeta

GROUP_VERSION = "policy/v1alpha1"


@dataclass
class PodDisruptionBudgetSpec:
    min_available: Optional[object] = None  # int | "50%"
    selector: Optional[LabelSelector] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruption_allowed: bool = False
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PodDisruptionBudgetSpec] = None
    status: Optional[PodDisruptionBudgetStatus] = None


scheme.add_known_type(GROUP_VERSION, "PodDisruptionBudget", PodDisruptionBudget)
