"""The v2 wire version: a second, restructured encoding of the core kinds.

Parity target: the reference's multi-version machinery — the same internal
objects served at several wire versions with conversion at the API boundary
(pkg/runtime/scheme.go:43, pkg/api/v1/conversion.go) and versioned defaulting
(pkg/api/v1/defaults.go). Storage and every component stay on internal types;
only the HTTP edge speaks v2.

v2's deliberate wire differences from v1 (so conversion is real, not a
field-copy):

- ``pod.spec.nodeName`` (a bare string) becomes ``pod.spec.nodeRef``, a full
  ObjectReference ``{kind: Node, name: ...}``.
- The scheduling-related spec fields (schedulerName, nodeSelector, affinity,
  tolerations) move under one ``pod.spec.scheduling`` struct.
- Defaulting on decode: restartPolicy defaults to "Always" and container
  ports default protocol "TCP" (v1 leaves both empty on the wire).

Node has no structural changes in v2 — it exercises the Converter's
reflective default path, Pod the registered-function path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.conversion import converter, defaulter
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict

API_VERSION = "v2"


# --- v2 kinds -----------------------------------------------------------------

@dataclass
class PodScheduling:
    """Scheduling knobs grouped under one struct in v2."""
    scheduler_name: str = ""
    node_selector: Optional[Dict[str, str]] = None
    affinity: Optional[api.Affinity] = None
    tolerations: Optional[List[api.Toleration]] = None


@dataclass
class PodSpec:
    containers: Optional[List[api.Container]] = None
    volumes: Optional[List[api.Volume]] = None
    node_ref: Optional[api.ObjectReference] = None
    restart_policy: str = ""
    termination_grace_period_seconds: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    service_account_name: str = ""
    host_network: bool = False
    scheduling: Optional[PodScheduling] = None


@dataclass
class Pod:
    metadata: Optional[api.ObjectMeta] = None
    spec: Optional[PodSpec] = None
    status: Optional[api.PodStatus] = None


@dataclass
class Node:
    """Structurally identical to v1 — converted by the reflective default."""
    metadata: Optional[api.ObjectMeta] = None
    spec: Optional[api.NodeSpec] = None
    status: Optional[api.NodeStatus] = None


# --- conversions (pkg/api/v1/conversion.go analogue) --------------------------

def _pod_to_v2(p: api.Pod, convert) -> Pod:
    s = p.spec
    spec2 = None
    if s is not None:
        scheduling = None
        if s.scheduler_name or s.node_selector or s.affinity or s.tolerations:
            scheduling = PodScheduling(
                scheduler_name=s.scheduler_name,
                node_selector=s.node_selector,
                affinity=s.affinity,
                tolerations=s.tolerations)
        spec2 = PodSpec(
            containers=s.containers, volumes=s.volumes,
            node_ref=(api.ObjectReference(kind="Node", name=s.node_name)
                      if s.node_name else None),
            restart_policy=s.restart_policy,
            termination_grace_period_seconds=s.termination_grace_period_seconds,
            active_deadline_seconds=s.active_deadline_seconds,
            service_account_name=s.service_account_name,
            host_network=s.host_network,
            scheduling=scheduling)
    return Pod(metadata=p.metadata, spec=spec2, status=p.status)


def _pod_from_v2(p: Pod, convert) -> api.Pod:
    s = p.spec
    spec1 = None
    if s is not None:
        sch = s.scheduling or PodScheduling()
        spec1 = api.PodSpec(
            containers=s.containers, volumes=s.volumes,
            node_name=(s.node_ref.name if s.node_ref else ""),
            restart_policy=s.restart_policy,
            termination_grace_period_seconds=s.termination_grace_period_seconds,
            active_deadline_seconds=s.active_deadline_seconds,
            service_account_name=s.service_account_name,
            host_network=s.host_network,
            scheduler_name=sch.scheduler_name,
            node_selector=sch.node_selector,
            affinity=sch.affinity,
            tolerations=sch.tolerations)
    return api.Pod(metadata=p.metadata, spec=spec1, status=p.status)


converter.register_pair(api.Pod, Pod, _pod_to_v2, _pod_from_v2)
# Node uses the Converter's reflective default path (no registration needed).


# --- defaulting (pkg/api/v1/defaults.go analogue) -----------------------------

def _default_pod(p: Pod) -> None:
    if p.spec is None:
        return
    if not p.spec.restart_policy:
        p.spec.restart_policy = "Always"
    for c in p.spec.containers or []:
        for port in c.ports or []:
            if not port.protocol:
                port.protocol = "TCP"


defaulter.register(Pod, _default_pod)


# --- scheme registration + the boundary codec ---------------------------------

scheme.add_known_type(API_VERSION, "Pod", Pod)
scheme.add_known_type(API_VERSION, "Node", Node)

_KINDS = {"pods": (Pod, api.Pod), "nodes": (Node, api.Node)}


class V2Codec:
    """Translates at the HTTP edge: versioned decode (+ defaulting) ->
    internal in; internal -> versioned encode out."""

    api_version = API_VERSION

    def __init__(self, resource: str):
        self.v2_cls, self.internal_cls = _KINDS[resource]

    def decode_into(self, _internal_cls, data: dict):
        obj2 = from_dict(self.v2_cls, data)
        defaulter.default(obj2)
        return converter.convert(obj2, self.internal_cls)

    def encode(self, internal_obj) -> dict:
        return scheme.encode(converter.convert(internal_obj, self.v2_cls))

    def encode_item(self, internal_obj) -> dict:
        """List items: no per-item TypeMeta, like v1 lists."""
        return to_dict(converter.convert(internal_obj, self.v2_cls))


def codec_for(resource: str) -> Optional[V2Codec]:
    if resource not in _KINDS:
        return None
    return V2Codec(resource)
