"""extensions/v1beta1 group.

Parity target: reference pkg/apis/extensions/types.go — Deployment (with
rolling-update strategy and rollback), DaemonSet, Ingress, ThirdPartyResource,
and the Scale subresource shared by rc/rs/deployments.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import LabelSelector, ObjectMeta, PodTemplateSpec

GROUP_VERSION = "extensions/v1beta1"

# Deployment strategy types (reference extensions/types.go DeploymentStrategyType)
RECREATE = "Recreate"
ROLLING_UPDATE = "RollingUpdate"


@dataclass
class RollingUpdateDeployment:
    """maxUnavailable/maxSurge accept an int or a percent string, like the
    reference's IntOrString."""
    max_unavailable: Optional[object] = None  # int | "25%"
    max_surge: Optional[object] = None


@dataclass
class DeploymentStrategy:
    type: str = ROLLING_UPDATE
    rolling_update: Optional[RollingUpdateDeployment] = None


@dataclass
class RollbackConfig:
    revision: int = 0


@dataclass
class DeploymentSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None
    strategy: Optional[DeploymentStrategy] = None
    min_ready_seconds: int = 0
    revision_history_limit: Optional[int] = None
    paused: bool = False
    rollback_to: Optional[RollbackConfig] = None


@dataclass
class DeploymentStatus:
    observed_generation: int = 0
    replicas: int = 0
    updated_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0


@dataclass
class Deployment:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[DeploymentSpec] = None
    status: Optional[DeploymentStatus] = None


@dataclass
class DeploymentRollback:
    name: str = ""
    updated_annotations: Optional[Dict[str, str]] = None
    rollback_to: Optional[RollbackConfig] = None


# revision annotation the deployment controller stamps on replica sets
# (reference deployment/util deploymentutil.RevisionAnnotation)
ANN_REVISION = "deployment.kubernetes.io/revision"


@dataclass
class DaemonSetSpec:
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    number_misscheduled: int = 0
    desired_number_scheduled: int = 0


@dataclass
class DaemonSet:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[DaemonSetSpec] = None
    status: Optional[DaemonSetStatus] = None


# --- Ingress -----------------------------------------------------------------

@dataclass
class IngressBackend:
    service_name: str = ""
    service_port: Optional[object] = None  # int | name


@dataclass
class HTTPIngressPath:
    path: str = ""
    backend: Optional[IngressBackend] = None


@dataclass
class HTTPIngressRuleValue:
    paths: Optional[List[HTTPIngressPath]] = None


@dataclass
class IngressRule:
    host: str = ""
    http: Optional[HTTPIngressRuleValue] = None


@dataclass
class IngressTLS:
    hosts: Optional[List[str]] = None
    secret_name: str = ""


@dataclass
class IngressSpec:
    backend: Optional[IngressBackend] = None
    tls: Optional[List[IngressTLS]] = None
    rules: Optional[List[IngressRule]] = None


@dataclass
class IngressStatus:
    load_balancer: Optional[dict] = None


@dataclass
class Ingress:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[IngressSpec] = None
    status: Optional[IngressStatus] = None


@dataclass
class APIVersion:
    name: str = ""


@dataclass
class ThirdPartyResource:
    metadata: Optional[ObjectMeta] = None
    description: str = ""
    versions: Optional[List[APIVersion]] = None


# --- Scale subresource (reference extensions/types.go Scale) ------------------

@dataclass
class ScaleSpec:
    replicas: int = 0


@dataclass
class ScaleStatus:
    replicas: int = 0
    selector: Optional[Dict[str, str]] = None


@dataclass
class Scale:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ScaleSpec] = None
    status: Optional[ScaleStatus] = None


for _kind, _cls in {
    "Deployment": Deployment,
    "DeploymentRollback": DeploymentRollback,
    "DaemonSet": DaemonSet,
    "Ingress": Ingress,
    "ThirdPartyResource": ThirdPartyResource,
    "Scale": Scale,
}.items():
    scheme.add_known_type(GROUP_VERSION, _kind, _cls)
