"""Federation API group (ubernetes).

Parity target: reference federation/apis/federation — the Cluster
resource: a member control plane registered with the federation by its
API endpoint, with a reachability condition the federation controller
maintains (federation/apis/federation/types.go Cluster/ClusterStatus).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import ObjectMeta

GROUP = "federation"
GROUP_VERSION = "federation/v1beta1"

CLUSTER_READY = "Ready"


@dataclass
class ClusterSpec:
    server_address: str = ""  # host:port of the member apiserver


@dataclass
class ClusterCondition:
    type: str = ""            # Ready
    status: str = ""          # True/False/Unknown
    reason: str = ""
    last_probe_time: Optional[str] = None


@dataclass
class ClusterStatus:
    conditions: Optional[List[ClusterCondition]] = None


@dataclass
class Cluster:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ClusterSpec] = None
    status: Optional[ClusterStatus] = None


scheme.add_known_type(GROUP_VERSION, "Cluster", Cluster)
