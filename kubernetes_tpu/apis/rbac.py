"""rbac.authorization.k8s.io/v1alpha1 group.

Parity target: reference pkg/apis/rbac/types.go — PolicyRule, Role,
RoleBinding, ClusterRole, ClusterRoleBinding. Consumed by the RBAC authorizer
(kubernetes_tpu.auth.authorizer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.api.types import ObjectMeta, ObjectReference

GROUP_VERSION = "rbac.authorization.k8s.io/v1alpha1"

VERB_ALL = "*"
APIGROUP_ALL = "*"
RESOURCE_ALL = "*"

# Subject kinds
USER_KIND = "User"
GROUP_KIND = "Group"
SERVICE_ACCOUNT_KIND = "ServiceAccount"


@dataclass
class PolicyRule:
    verbs: Optional[List[str]] = None
    api_groups: Optional[List[str]] = None
    resources: Optional[List[str]] = None
    resource_names: Optional[List[str]] = None
    non_resource_urls: Optional[List[str]] = None


@dataclass
class Subject:
    kind: str = ""
    name: str = ""
    namespace: str = ""


@dataclass
class Role:
    metadata: Optional[ObjectMeta] = None
    rules: Optional[List[PolicyRule]] = None


@dataclass
class RoleBinding:
    metadata: Optional[ObjectMeta] = None
    subjects: Optional[List[Subject]] = None
    role_ref: Optional[ObjectReference] = None


@dataclass
class ClusterRole:
    metadata: Optional[ObjectMeta] = None
    rules: Optional[List[PolicyRule]] = None


@dataclass
class ClusterRoleBinding:
    metadata: Optional[ObjectMeta] = None
    subjects: Optional[List[Subject]] = None
    role_ref: Optional[ObjectReference] = None


for _kind, _cls in {
    "Role": Role,
    "RoleBinding": RoleBinding,
    "ClusterRole": ClusterRole,
    "ClusterRoleBinding": ClusterRoleBinding,
}.items():
    scheme.add_known_type(GROUP_VERSION, _kind, _cls)
