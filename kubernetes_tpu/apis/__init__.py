"""API groups beyond core v1.

Parity target: reference pkg/apis/ (extensions, batch, autoscaling, apps,
policy, rbac, componentconfig — SURVEY §2.1). Each module registers its kinds
into the shared serialization Scheme under the group's wire apiVersion, the
same group-install pattern as pkg/apis/<g>/install.
"""

from kubernetes_tpu.apis import (  # noqa: F401  (import = register in scheme)
    apps,
    autoscaling,
    batch,
    componentconfig,
    extensions,
    federation,
    policy,
    rbac,
)

GROUPS = {
    "extensions": "extensions/v1beta1",
    "batch": "batch/v1",
    "autoscaling": "autoscaling/v1",
    "apps": "apps/v1alpha1",
    "policy": "policy/v1alpha1",
    "rbac.authorization.k8s.io": "rbac.authorization.k8s.io/v1alpha1",
    "componentconfig": "componentconfig/v1alpha1",
    "federation": "federation/v1beta1",
}
