"""componentconfig/v1alpha1 group.

Parity target: reference pkg/apis/componentconfig/types.go — component flags
are themselves versioned API objects (KubeSchedulerConfiguration built via
Scheme conversion in plugin/cmd/kube-scheduler/app/options/options.go:40-74,
exported live at /configz). The scheduler/proxy/kubelet entry points decode
these and the configz registry serves them back.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kubernetes_tpu.api.serialization import scheme

GROUP_VERSION = "componentconfig/v1alpha1"


@dataclass
class KubeSchedulerConfiguration:
    scheduler_name: str = "default-scheduler"
    algorithm_provider: str = "DefaultProvider"
    policy_config_file: str = ""
    hard_pod_affinity_symmetric_weight: int = 1
    failure_domains: str = ""
    kube_api_qps: float = 50.0
    kube_api_burst: int = 100
    leader_election: Optional["LeaderElectionConfiguration"] = None
    port: int = 10251
    master: str = "http://127.0.0.1:8080"
    # TPU decision plane (no reference analog): enable the batched kernel
    # and its shapes
    tpu_backend: bool = False
    tpu_batch_window_ms: int = 50
    batch_size: int = 4096


@dataclass
class APIServerConfiguration:
    bind_address: str = "127.0.0.1"
    port: int = 8080
    data_dir: str = ""          # empty = memory-only store
    max_in_flight: int = 400
    watcher_queue: int = 4096
    admission_control: str = ""  # comma-separated plugin names
    tls_cert_file: str = ""      # secure serving when set
    tls_private_key_file: str = ""
    client_ca_file: str = ""     # verified client certs -> x509 identities
    token_auth_file: str = ""    # CSV: token,user,uid[,groups]
    authorization_mode: str = ""  # "", "RBAC", "ABAC", "AlwaysAllow"
    authorization_policy_file: str = ""  # ABAC policy


@dataclass
class ControllerManagerConfiguration:
    port: int = 10252
    leader_elect: bool = False


@dataclass
class LeaderElectionConfiguration:
    leader_elect: bool = False
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0


@dataclass
class KubeProxyConfiguration:
    bind_address: str = "0.0.0.0"
    mode: str = "iptables"  # iptables | userspace
    sync_period_seconds: float = 30.0
    oom_score_adj: Optional[int] = None


@dataclass
class KubeletConfiguration:
    address: str = "0.0.0.0"
    port: int = 10250
    max_pods: int = 110
    sync_frequency_seconds: float = 60.0
    node_status_update_frequency_seconds: float = 10.0
    image_gc_high_threshold_percent: int = 90
    image_gc_low_threshold_percent: int = 80
    eviction_hard: str = "memory.available<100Mi"


for _kind, _cls in {
    "KubeSchedulerConfiguration": KubeSchedulerConfiguration,
    "LeaderElectionConfiguration": LeaderElectionConfiguration,
    "KubeProxyConfiguration": KubeProxyConfiguration,
    "KubeletConfiguration": KubeletConfiguration,
    "APIServerConfiguration": APIServerConfiguration,
    "ControllerManagerConfiguration": ControllerManagerConfiguration,
}.items():
    scheme.add_known_type(GROUP_VERSION, _kind, _cls)
