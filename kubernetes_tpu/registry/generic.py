"""Generic registry store and the built-in resource table.

The reference instantiates registry.Store per resource with strategy hooks
(pkg/registry/generic/registry/store.go:65-105); here ResourceDef carries the
same knobs (key layout, validation, create/update preparation, selectable
fields) and Registry executes CRUD against storage.MemStore, returning typed
objects. The pod binding subresource lives here too: a guaranteed_update that
sets spec.nodeName iff empty and flips the PodScheduled condition atomically
(reference assignPod/setPodHostAndAnnotations, pkg/registry/pod/etcd/etcd.go:
146-189) — the scheduler's single write.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field
from typing import Callable, Dict, List, Optional, Tuple, Type

from kubernetes_tpu.api import fields as fieldsel
from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import validation
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict
from kubernetes_tpu.storage import Conflict, KeyExists, KeyNotFound, MemStore
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso


@dataclass
class ResourceDef:
    """Everything the generic store needs to serve one resource."""

    name: str                 # plural, e.g. "pods"
    kind: str                 # "Pod"
    cls: Type
    namespaced: bool = True
    list_kind: str = ""       # "PodList"
    api_version: str = "v1"
    validator: Optional[Callable] = None
    prepare_for_create: Optional[Callable] = None  # (obj) -> None, mutate
    prepare_for_update: Optional[Callable] = None  # (new, old) -> None

    def __post_init__(self):
        if not self.list_kind:
            self.list_kind = self.kind + "List"

    def key(self, namespace: str, name: str) -> str:
        if self.namespaced:
            return f"/{self.name}/{namespace}/{name}"
        return f"/{self.name}/{name}"

    def prefix(self, namespace: str = "") -> str:
        if self.namespaced and namespace:
            return f"/{self.name}/{namespace}/"
        return f"/{self.name}/"


class RegistryError(Exception):
    def __init__(self, code: int, reason: str, message: str):
        self.code = code
        self.reason = reason
        self.message = message
        super().__init__(message)


def not_found(kind, name):
    return RegistryError(404, "NotFound", f'{kind} "{name}" not found')


def already_exists(kind, name):
    return RegistryError(409, "AlreadyExists", f'{kind} "{name}" already exists')


def conflict(kind, name, msg):
    return RegistryError(409, "Conflict", f'{kind} "{name}": {msg}')


def invalid(msg):
    return RegistryError(422, "Invalid", msg)


def bad_request(msg):
    return RegistryError(400, "BadRequest", msg)


_uid_lock = threading.Lock()
_uid_counter = [0]


def _new_uid() -> str:
    with _uid_lock:
        _uid_counter[0] += 1
        return f"uid-{_uid_counter[0]:08x}"


def _pod_prepare_create(pod: api.Pod):
    if pod.status is None:
        pod.status = api.PodStatus()
    if not pod.status.phase:
        pod.status.phase = api.POD_PENDING
    # nodeName is only settable via /bindings (reference pod strategy
    # PrepareForCreate resets Status; binding sets the host)


def _pod_prepare_update(new: api.Pod, old: api.Pod):
    # spec.nodeName may never change via PUT — assignment happens only
    # through the binding subresource's CAS (which bypasses this hook), so a
    # read-modify-write client can't race the scheduler into an assignment
    old_nn = old.spec.node_name if old.spec else ""
    new_nn = new.spec.node_name if new.spec else ""
    if old_nn != new_nn:
        raise invalid("spec.nodeName: may only be set via the bindings subresource")
    # everything else in the spec is immutable except container images
    # (reference ValidatePodUpdate, validation.go)
    try:
        validation.validate_pod_update(new, old)
    except validation.ValidationError as e:
        raise invalid(str(e)) from None


def _service_prepare_update(new: api.Service, old: api.Service):
    # clusterIP is immutable once set (reference service strategy); an
    # update that omits it inherits the allocation rather than clearing it
    old_ip = old.spec.cluster_ip if old.spec else ""
    if new.spec is None:
        new.spec = api.ServiceSpec()
    if not new.spec.cluster_ip:
        new.spec.cluster_ip = old_ip
    elif old_ip and new.spec.cluster_ip != old_ip:
        raise invalid("spec.clusterIP: field is immutable")


def _event_prepare_create(ev: api.Event):
    if not ev.first_timestamp:
        ev.first_timestamp = _now_iso()
    if not ev.last_timestamp:
        ev.last_timestamp = ev.first_timestamp
    if not ev.count:
        ev.count = 1


RESOURCES: Dict[str, ResourceDef] = {}


def _register(rd: ResourceDef):
    RESOURCES[rd.name] = rd
    return rd


_register(ResourceDef("pods", "Pod", api.Pod, validator=validation.validate_pod,
                      prepare_for_create=_pod_prepare_create,
                      prepare_for_update=_pod_prepare_update))
_register(ResourceDef("nodes", "Node", api.Node, namespaced=False,
                      validator=validation.validate_node))
_register(ResourceDef("services", "Service", api.Service,
                      validator=validation.validate_service,
                      prepare_for_update=_service_prepare_update))
_register(ResourceDef("endpoints", "Endpoints", api.Endpoints,
                      list_kind="EndpointsList"))
_register(ResourceDef("replicationcontrollers", "ReplicationController",
                      api.ReplicationController,
                      validator=validation.validate_replication_controller))
_register(ResourceDef("replicasets", "ReplicaSet", api.ReplicaSet,
                      api_version="extensions/v1beta1"))
_register(ResourceDef("namespaces", "Namespace", api.Namespace, namespaced=False,
                      validator=validation.validate_namespace))
_register(ResourceDef("events", "Event", api.Event,
                      prepare_for_create=_event_prepare_create))
_register(ResourceDef("persistentvolumes", "PersistentVolume",
                      api.PersistentVolume, namespaced=False))
_register(ResourceDef("persistentvolumeclaims", "PersistentVolumeClaim",
                      api.PersistentVolumeClaim))
_register(ResourceDef("secrets", "Secret", api.Secret,
                      validator=validation.validate_secret))
_register(ResourceDef("configmaps", "ConfigMap", api.ConfigMap))
_register(ResourceDef("serviceaccounts", "ServiceAccount", api.ServiceAccount))
_register(ResourceDef("limitranges", "LimitRange", api.LimitRange,
                      validator=validation.validate_limit_range))
_register(ResourceDef("resourcequotas", "ResourceQuota", api.ResourceQuota,
                      validator=validation.validate_resource_quota))


def _register_group_resources():
    """Resources from the non-core API groups (reference pkg/apis/<g>/install
    + pkg/registry per-resource packages; SURVEY §2.1/§2.3)."""
    from kubernetes_tpu.apis import apps, autoscaling, batch, extensions, policy, rbac

    _register(ResourceDef("deployments", "Deployment", extensions.Deployment,
                          api_version=extensions.GROUP_VERSION,
                          validator=validation.validate_deployment))
    _register(ResourceDef("daemonsets", "DaemonSet", extensions.DaemonSet,
                          api_version=extensions.GROUP_VERSION,
                          validator=validation.validate_daemonset))
    _register(ResourceDef("ingresses", "Ingress", extensions.Ingress,
                          api_version=extensions.GROUP_VERSION,
                          list_kind="IngressList"))
    _register(ResourceDef("thirdpartyresources", "ThirdPartyResource",
                          extensions.ThirdPartyResource, namespaced=False,
                          api_version=extensions.GROUP_VERSION))
    _register(ResourceDef("jobs", "Job", batch.Job,
                          api_version=batch.GROUP_VERSION,
                          validator=validation.validate_job))
    _register(ResourceDef("scheduledjobs", "ScheduledJob", batch.ScheduledJob,
                          api_version=batch.GROUP_VERSION_V2,
                          validator=validation.validate_scheduled_job))
    _register(ResourceDef("horizontalpodautoscalers", "HorizontalPodAutoscaler",
                          autoscaling.HorizontalPodAutoscaler,
                          api_version=autoscaling.GROUP_VERSION,
                          validator=validation.validate_hpa))
    _register(ResourceDef("petsets", "PetSet", apps.PetSet,
                          api_version=apps.GROUP_VERSION,
                          validator=validation.validate_petset))
    _register(ResourceDef("poddisruptionbudgets", "PodDisruptionBudget",
                          policy.PodDisruptionBudget,
                          api_version=policy.GROUP_VERSION))
    _register(ResourceDef("roles", "Role", rbac.Role,
                          api_version=rbac.GROUP_VERSION))
    _register(ResourceDef("rolebindings", "RoleBinding", rbac.RoleBinding,
                          api_version=rbac.GROUP_VERSION))
    _register(ResourceDef("clusterroles", "ClusterRole", rbac.ClusterRole,
                          namespaced=False, api_version=rbac.GROUP_VERSION))
    _register(ResourceDef("clusterrolebindings", "ClusterRoleBinding",
                          rbac.ClusterRoleBinding, namespaced=False,
                          api_version=rbac.GROUP_VERSION))

    from kubernetes_tpu.apis import federation
    _register(ResourceDef("clusters", "Cluster", federation.Cluster,
                          namespaced=False,
                          api_version=federation.GROUP_VERSION))


_register_group_resources()


class ServiceIPAllocator:
    """Cluster-IP allocation from the service CIDR (reference
    pkg/registry/service/ipallocator). Seeded lazily from the live service
    list so a registry rebuilt from a durable store doesn't re-hand-out
    taken IPs."""

    def __init__(self, cidr: str = "10.96.0.0/12"):
        import ipaddress
        self.net = ipaddress.ip_network(cidr)
        self._used: set = set()
        self._lock = threading.Lock()
        self._cursor = 0
        self._size = self.net.num_addresses - 2  # skip network + broadcast

    def seed(self, ips) -> None:
        with self._lock:
            self._used.update(ip for ip in ips if ip and ip != "None")

    def allocate(self) -> str:
        with self._lock:
            for _ in range(self._size):
                self._cursor = self._cursor % self._size + 1
                ip = str(self.net[self._cursor])
                if ip not in self._used:
                    self._used.add(ip)
                    return ip
        raise invalid(f"service CIDR {self.net} exhausted")

    def claim(self, ip: str) -> None:
        import ipaddress
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            raise invalid(f"spec.clusterIP: invalid IP {ip!r}") from None
        if addr not in self.net or addr in (self.net.network_address,
                                            self.net.broadcast_address):
            raise invalid(f"spec.clusterIP: {ip} not in service CIDR {self.net}")
        with self._lock:
            if ip in self._used:
                raise invalid(f"spec.clusterIP: {ip} already allocated")
            self._used.add(ip)

    def release(self, ip: str) -> None:
        with self._lock:
            self._used.discard(ip)


class Registry:
    """CRUD over typed objects, backed by one MemStore."""

    def __init__(self, store: Optional[MemStore] = None):
        self.store = store or MemStore()
        self.service_ips = ServiceIPAllocator()
        self._ips_seeded = False

    def _seed_service_ips(self) -> None:
        if self._ips_seeded:
            return
        items, _ = self.list("services")
        self.service_ips.seed(
            s.spec.cluster_ip for s in items if s.spec is not None)
        self._ips_seeded = True

    def _prepare_service(self, svc: api.Service) -> None:
        """Allocate/claim the cluster IP (skydns + proxy both key off it).
        "None" = headless: no allocation, DNS answers per-endpoint."""
        self._seed_service_ips()
        if svc.spec is None:
            svc.spec = api.ServiceSpec()
        ip = svc.spec.cluster_ip
        if ip == "None":
            return
        if ip:
            self.service_ips.claim(ip)
        else:
            svc.spec.cluster_ip = self.service_ips.allocate()

    def _def(self, resource: str) -> ResourceDef:
        try:
            return RESOURCES[resource]
        except KeyError:
            raise not_found("resource", resource) from None

    # --- CRUD ----------------------------------------------------------------

    def create(self, resource: str, obj, namespace: str = ""):
        rd = self._def(resource)
        if not isinstance(obj, rd.cls):
            raise bad_request(f"expected {rd.kind}, got {type(obj).__name__}")
        meta = obj.metadata or api.ObjectMeta()
        obj.metadata = meta
        if rd.namespaced:
            meta.namespace = meta.namespace or namespace or "default"
        if not meta.name and meta.generate_name:
            meta.name = meta.generate_name + _new_uid()[4:]
        if rd.prepare_for_create:
            rd.prepare_for_create(obj)
        allocated_ip = ""
        if rd.name == "services":
            self._prepare_service(obj)
            # on any later failure the IP must go back — auto-allocated OR
            # explicitly claimed, else a rejected manifest leaks it forever
            if obj.spec and obj.spec.cluster_ip != "None":
                allocated_ip = obj.spec.cluster_ip
        if rd.validator:
            try:
                rd.validator(obj)
            except validation.ValidationError as e:
                if allocated_ip:
                    self.service_ips.release(allocated_ip)
                raise invalid(str(e)) from None
        meta.uid = meta.uid or _new_uid()
        meta.creation_timestamp = meta.creation_timestamp or _now_iso()
        key = rd.key(meta.namespace, meta.name)
        try:
            rv = self.store.create(key, to_dict(obj))
        except KeyExists:
            if allocated_ip:
                self.service_ips.release(allocated_ip)
            raise already_exists(rd.kind, meta.name) from None
        meta.resource_version = str(rv)
        return obj

    def get(self, resource: str, name: str, namespace: str = ""):
        rd = self._def(resource)
        try:
            d, rv = self.store.get(rd.key(namespace, name))
        except KeyNotFound:
            raise not_found(rd.kind, name) from None
        return self._decode(rd, d, rv)

    def list(self, resource: str, namespace: str = "",
             label_selector: Optional[labelsel.Selector] = None,
             field_selector: Optional[fieldsel.FieldSelector] = None
             ) -> Tuple[list, int]:
        rd = self._def(resource)
        raw, rv = self.store.list(rd.prefix(namespace))
        out = []
        for d, item_rv in raw:
            obj = self._decode(rd, d, item_rv)
            if self._matches(obj, label_selector, field_selector):
                out.append(obj)
        return out, rv

    def update(self, resource: str, obj, namespace: str = ""):
        rd = self._def(resource)
        meta = obj.metadata or api.ObjectMeta()
        key = rd.key(meta.namespace or namespace, meta.name)
        expect = int(meta.resource_version) if meta.resource_version else None
        try:
            old_d, old_rv = self.store.get(key)
        except KeyNotFound:
            raise not_found(rd.kind, meta.name) from None
        old = self._decode(rd, old_d, old_rv)
        if rd.prepare_for_update:
            rd.prepare_for_update(obj, old)
        if rd.validator:
            try:
                rd.validator(obj)
            except validation.ValidationError as e:
                raise invalid(str(e)) from None
        # preserve server-managed fields
        meta.uid = old.metadata.uid
        meta.creation_timestamp = old.metadata.creation_timestamp
        try:
            rv = self.store.update(key, to_dict(obj), expect_rv=expect)
        except Conflict as e:
            raise conflict(rd.kind, meta.name, str(e)) from None
        meta.resource_version = str(rv)
        return obj

    def guaranteed_update(self, resource: str, name: str, namespace: str,
                          fn: Callable, max_retries: int = 10):
        """Typed CAS loop: fn(typed_obj) -> typed_obj or None (no-op). The
        typed object fn sees carries its current resourceVersion so fn can
        enforce client preconditions."""
        rd = self._def(resource)
        key = rd.key(namespace, name)
        result = {}

        def raw_fn(d: dict, rv: int):
            obj = self._decode(rd, d, rv)
            new = fn(obj)
            result["obj"] = new if new is not None else obj
            return None if new is None else to_dict(new)

        try:
            _, new_rv = self.store.guaranteed_update(key, raw_fn,
                                                     max_retries=max_retries)
        except KeyNotFound:
            raise not_found(rd.kind, name) from None
        except Conflict as e:
            raise conflict(rd.kind, name, str(e)) from None
        out = result["obj"]
        out.metadata.resource_version = str(new_rv)
        return out

    def delete(self, resource: str, name: str, namespace: str = ""):
        rd = self._def(resource)
        try:
            d, rv = self.store.delete(rd.key(namespace, name))
        except KeyNotFound:
            raise not_found(rd.kind, name) from None
        obj = self._decode(rd, d, rv)
        if rd.name == "services" and obj.spec is not None \
                and obj.spec.cluster_ip not in ("", "None"):
            self.service_ips.release(obj.spec.cluster_ip)
        return obj

    def watch(self, resource: str, namespace: str = "",
              since_rv: Optional[int] = None):
        rd = self._def(resource)
        return self.store.watch(rd.prefix(namespace), since_rv)

    # --- subresources --------------------------------------------------------

    def bind_pod(self, binding: api.Binding, namespace: str) -> None:
        """POST /bindings: atomically set pod.spec.nodeName iff empty and mark
        PodScheduled=True (reference etcd.go:146-189)."""
        try:
            validation.validate_binding(binding)
        except validation.ValidationError as e:
            raise invalid(str(e)) from None
        pod_name = binding.metadata.name if binding.metadata else ""
        if not pod_name:
            raise invalid("metadata.name (pod name) required")
        node_name = binding.target.name

        def assign(pod: api.Pod):
            if pod.spec is None:
                pod.spec = api.PodSpec()
            if pod.spec.node_name and pod.spec.node_name != node_name:
                raise conflict("Pod", pod_name,
                               f"is already assigned to node {pod.spec.node_name!r}")
            if pod.spec.node_name == node_name:
                return None  # idempotent
            pod.spec.node_name = node_name
            if pod.status is None:
                pod.status = api.PodStatus()
            _set_pod_condition(pod, api.POD_SCHEDULED, api.CONDITION_TRUE, "", "")
            return pod

        self.guaranteed_update("pods", pod_name, namespace, assign)

    # scale subresource (reference extensions Scale registry; kubectl scale
    # and the HPA controller go through this)
    SCALABLE = {"replicationcontrollers", "replicasets", "deployments", "petsets"}

    def get_scale(self, resource: str, name: str, namespace: str = ""):
        from kubernetes_tpu.apis import extensions as ext
        if resource not in self.SCALABLE:
            raise bad_request(f"resource {resource!r} has no scale subresource")
        obj = self.get(resource, name, namespace)
        return self._scale_view(obj, ext)

    def update_scale(self, resource: str, name: str, namespace: str, scale):
        from kubernetes_tpu.apis import extensions as ext
        if resource not in self.SCALABLE:
            raise bad_request(f"resource {resource!r} has no scale subresource")
        want = scale.spec.replicas if scale.spec else 0
        expect_rv = scale.metadata.resource_version if scale.metadata else ""

        if scale.spec is None:
            raise invalid("spec: required")
        if not isinstance(want, int) or want < 0:
            raise invalid("spec.replicas: must be a non-negative integer")
        rd = self._def(resource)

        def set_replicas(cur):
            # optimistic concurrency: a stale Scale must 409, not clobber a
            # concurrent scaling (reference Scale storage honors the RV)
            if expect_rv and cur.metadata.resource_version != expect_rv:
                raise conflict(resource, name,
                               f"scale rv {expect_rv} != current "
                               f"{cur.metadata.resource_version}")
            if cur.spec is None:
                raise invalid("spec: required")
            cur.spec.replicas = want
            if rd.validator:
                try:
                    rd.validator(cur)
                except validation.ValidationError as e:
                    raise invalid(str(e)) from None
            return cur

        obj = self.guaranteed_update(resource, name, namespace, set_replicas)
        return self._scale_view(obj, ext)

    @staticmethod
    def _scale_view(obj, ext):
        sel = obj.spec.selector if obj.spec else None
        if isinstance(sel, api.LabelSelector):
            sel = sel.match_labels
        return ext.Scale(
            metadata=api.ObjectMeta(name=obj.metadata.name,
                                    namespace=obj.metadata.namespace,
                                    resource_version=obj.metadata.resource_version),
            spec=ext.ScaleSpec(replicas=(obj.spec.replicas or 0) if obj.spec else 0),
            status=ext.ScaleStatus(
                replicas=(obj.status.replicas if obj.status else 0) or 0,
                selector=sel))

    def rollback_deployment(self, name: str, namespace: str, rollback) -> None:
        """POST /deployments/{name}/rollback — records spec.rollbackTo for the
        deployment controller to act on (reference extensions
        DeploymentRollback storage)."""
        from kubernetes_tpu.apis import extensions as ext

        def set_rollback(d):
            if d.spec is None:
                raise invalid("spec: required")
            d.spec.rollback_to = rollback.rollback_to or ext.RollbackConfig(revision=0)
            return d

        self.guaranteed_update("deployments", name, namespace, set_rollback)

    def update_status(self, resource: str, obj, namespace: str = ""):
        """PUT /{resource}/{name}/status — replaces only .status."""
        rd = self._def(resource)
        meta = obj.metadata or api.ObjectMeta()

        expect_rv = meta.resource_version

        def set_status(cur):
            # honor the optimistic-concurrency precondition like plain PUT:
            # a stale status writer must get 409, not silently win
            if expect_rv and cur.metadata.resource_version != expect_rv:
                raise conflict(rd.kind, meta.name,
                               f"rv {expect_rv} != current {cur.metadata.resource_version}")
            cur.status = obj.status
            if rd.validator:
                try:
                    rd.validator(cur)
                except validation.ValidationError as e:
                    raise invalid(str(e)) from None
            return cur

        return self.guaranteed_update(resource, meta.name,
                                      meta.namespace or namespace, set_status)

    # --- helpers -------------------------------------------------------------

    def _decode(self, rd: ResourceDef, d: dict, rv: Optional[int]):
        obj = from_dict(rd.cls, d)
        if rv is not None:
            if obj.metadata is None:
                obj.metadata = api.ObjectMeta()
            obj.metadata.resource_version = str(rv)
        return obj

    @staticmethod
    def _matches(obj, label_selector, field_selector) -> bool:
        if label_selector is not None and not label_selector.empty():
            lbls = (obj.metadata.labels or {}) if obj.metadata else {}
            if not label_selector.matches(lbls):
                return False
        if field_selector is not None and not field_selector.empty():
            if not field_selector.matches(api.object_fields(obj)):
                return False
        return True


def _set_pod_condition(pod: api.Pod, ctype: str, status: str, reason: str,
                       message: str):
    """Idempotent condition upsert (reference api.UpdatePodCondition)."""
    conds = list(pod.status.conditions or [])
    for i, c in enumerate(conds):
        if c.type == ctype:
            if c.status == status and c.reason == reason:
                return
            conds[i] = api.PodCondition(type=ctype, status=status, reason=reason,
                                        message=message,
                                        last_transition_time=_now_iso())
            pod.status.conditions = conds
            return
    conds.append(api.PodCondition(type=ctype, status=status, reason=reason,
                                  message=message, last_transition_time=_now_iso()))
    pod.status.conditions = conds


set_pod_condition = _set_pod_condition
