"""L1 resource registries: generic REST store + per-resource strategies.

Parity target: reference pkg/registry/generic/registry/store.go (the
templated Store every resource instantiates) and the per-resource strategy
packages (pkg/registry/pod, pkg/registry/node, ...), including the pod
BindingREST (pkg/registry/pod/etcd/etcd.go:118-189).
"""

from kubernetes_tpu.registry.generic import ResourceDef, Registry, RESOURCES
