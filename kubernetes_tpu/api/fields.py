"""Field selector algebra.

Parity target: reference pkg/fields — equality matching over a flat set of
per-object field paths. The load-bearing use is the scheduler's unassigned-pod
ListWatch (`spec.nodeName=`) and kubelet's assigned-pod watch
(`spec.nodeName=<me>`); also `status.phase`, `metadata.name` filters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Optional


class FieldSelectorError(ValueError):
    pass


@dataclass(frozen=True)
class FieldRequirement:
    key: str
    value: str
    negate: bool = False

    def matches(self, fields: Mapping[str, str]) -> bool:
        got = fields.get(self.key, "")
        return (got != self.value) if self.negate else (got == self.value)


@dataclass(frozen=True)
class FieldSelector:
    requirements: tuple = ()

    def matches(self, fields: Mapping[str, str]) -> bool:
        return all(r.matches(fields) for r in self.requirements)

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        return ",".join(
            f"{r.key}!={r.value}" if r.negate else f"{r.key}={r.value}"
            for r in self.requirements
        )


def everything() -> FieldSelector:
    return FieldSelector(())


def parse_field_selector(s: Optional[str]) -> FieldSelector:
    if not s or not s.strip():
        return everything()
    reqs = []
    for clause in s.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if "!=" in clause:
            k, v = clause.split("!=", 1)
            reqs.append(FieldRequirement(k.strip(), v.strip(), negate=True))
        elif "==" in clause:
            k, v = clause.split("==", 1)
            reqs.append(FieldRequirement(k.strip(), v.strip()))
        elif "=" in clause:
            k, v = clause.split("=", 1)
            reqs.append(FieldRequirement(k.strip(), v.strip()))
        else:
            raise FieldSelectorError(f"invalid field selector clause: {clause!r}")
    return FieldSelector(tuple(reqs))
