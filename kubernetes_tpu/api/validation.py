"""Declarative validation.

Parity target: reference pkg/api/validation/validation.go (3,140 ln) — the
load-bearing subset: object meta (DNS-1123 names, namespace rules), pod spec
(containers present, unique names, resource requests parseable and
non-negative, port ranges), node, service, and binding validation
(ValidatePodBinding)."""

from __future__ import annotations

import re
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import QuantityError, parse_fraction

_DNS1123_LABEL = re.compile(r"[a-z0-9]([-a-z0-9]*[a-z0-9])?\Z")
_DNS1123_SUBDOMAIN = re.compile(r"[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*\Z")
_QUALIFIED_NAME = re.compile(r"([A-Za-z0-9][-A-Za-z0-9_./]*)?[A-Za-z0-9]\Z")
# label VALUES: up to 63 chars, alnum ends, -_. inside, empty allowed
# (reference validation.IsValidLabelValue)
_LABEL_VALUE = re.compile(r"(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?\Z")
# port names: IANA_SVC_NAME — <=15 lowercase alnum/-, at least one letter,
# no leading/trailing/double dash (reference validation.IsValidPortName)
_IANA_SVC = re.compile(r"[a-z0-9]([a-z0-9-]*[a-z0-9])?\Z")
# env var names (reference validation.IsCIdentifier)
_C_IDENTIFIER = re.compile(r"[A-Za-z_][A-Za-z0-9_]*\Z")

# reference api/validation/objectmeta TotalAnnotationSizeLimitB
_MAX_ANNOTATION_BYTES = 256 * 1024


def _valid_label_value(v) -> bool:
    return (isinstance(v, str) and len(v) <= 63
            and bool(_LABEL_VALUE.match(v)))


def _valid_qualified_name(key: str) -> bool:
    """Label/annotation keys: [prefix/]name with the prefix a DNS-1123
    subdomain (<=253) and the name a qualified name (<=63)."""
    if "/" in key:
        prefix, _, name = key.partition("/")
        if not prefix or len(prefix) > 253 \
                or not _DNS1123_SUBDOMAIN.match(prefix):
            return False
    else:
        name = key
    return bool(name) and len(name) <= 63 \
        and bool(_QUALIFIED_NAME.match(name)) and "/" not in name


def _valid_port_name(name: str) -> bool:
    return (len(name) <= 15 and bool(_IANA_SVC.match(name))
            and "--" not in name
            and any(ch.isalpha() for ch in name))


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def _check(errs, cond, msg):
    if not cond:
        errs.append(msg)


def validate_object_meta(meta: Optional[api.ObjectMeta], namespaced: bool,
                         errs: List[str], prefix: str = "metadata"):
    if meta is None:
        errs.append(f"{prefix}: required")
        return
    name = meta.name
    _check(errs, bool(name or meta.generate_name), f"{prefix}.name: required")
    if name:
        _check(errs, len(name) <= 253 and _DNS1123_SUBDOMAIN.match(name),
               f"{prefix}.name: must be a DNS-1123 subdomain: {name!r}")
    elif meta.generate_name:
        # generateName is a prefix; a random suffix is appended, so a trailing
        # '-' is conventional and must validate (reference ValidateObjectMeta)
        gen = meta.generate_name.rstrip("-")
        _check(errs, len(meta.generate_name) <= 247 and (not gen or _DNS1123_SUBDOMAIN.match(gen)),
               f"{prefix}.generateName: must be a DNS-1123 subdomain prefix: {meta.generate_name!r}")
    if namespaced:
        _check(errs, bool(meta.namespace), f"{prefix}.namespace: required")
        if meta.namespace:
            _check(errs, _DNS1123_LABEL.match(meta.namespace),
                   f"{prefix}.namespace: must be a DNS-1123 label: {meta.namespace!r}")
    else:
        _check(errs, not meta.namespace, f"{prefix}.namespace: not allowed on cluster-scoped object")
    for k, v in (meta.labels or {}).items():
        _check(errs, isinstance(k, str) and _valid_qualified_name(k),
               f"{prefix}.labels: invalid key {k!r}")
        _check(errs, _valid_label_value(v),
               f"{prefix}.labels[{k}]: invalid value {v!r}")
    total = 0
    for k, v in (meta.annotations or {}).items():
        _check(errs, isinstance(k, str) and _valid_qualified_name(k),
               f"{prefix}.annotations: invalid key {k!r}")
        if not isinstance(v, str):
            errs.append(f"{prefix}.annotations[{k}]: value must be a string")
            continue
        # BYTES, not characters (reference TotalAnnotationSizeLimitB)
        total += len(str(k).encode()) + len(v.encode())
    _check(errs, total <= _MAX_ANNOTATION_BYTES,
           f"{prefix}.annotations: total size {total} exceeds 256KB")


def _validate_resource_list(rl, errs, prefix):
    for k, v in (rl or {}).items():
        try:
            # exact fraction: ceil-to-int would round "-100m" up to 0
            q = parse_fraction(v)
            _check(errs, q >= 0, f"{prefix}.{k}: must be non-negative")
        except QuantityError:
            errs.append(f"{prefix}.{k}: invalid quantity {v!r}")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _validate_probe(probe, errs, prefix):
    if probe is None:
        return
    for fld in ("initial_delay_seconds", "timeout_seconds", "period_seconds",
                "success_threshold", "failure_threshold"):
        v = getattr(probe, fld, 0)
        _check(errs, v is None or (_is_num(v) and v >= 0),
               f"{prefix}.{fld}: must be non-negative")
    handlers = sum(1 for h in (probe.exec, probe.http_get, probe.tcp_socket)
                   if h is not None)
    _check(errs, handlers == 1,
           f"{prefix}: exactly one handler (exec/httpGet/tcpSocket) required")


def _validate_requests_vs_limits(c, errs, prefix):
    """Per-resource limits must cover requests (ValidateResourceRequirements)."""
    if not c.resources or not c.resources.limits or not c.resources.requests:
        return
    for k, req in c.resources.requests.items():
        lim = c.resources.limits.get(k)
        if lim is None:
            continue
        try:
            _check(errs, parse_fraction(req) <= parse_fraction(lim),
                   f"{prefix}.resources.requests.{k}: exceeds limit")
        except QuantityError:
            pass  # reported by _validate_resource_list


def validate_pod(pod: api.Pod) -> None:
    errs: List[str] = []
    validate_object_meta(pod.metadata, True, errs)
    spec = pod.spec
    if spec is None or not spec.containers:
        errs.append("spec.containers: at least one container required")
        if errs:
            raise ValidationError(errs)
        return
    _check(errs, spec.restart_policy in ("", "Always", "OnFailure", "Never"),
           f"spec.restartPolicy: invalid {spec.restart_policy!r}")
    if spec.termination_grace_period_seconds is not None:
        _check(errs, _is_num(spec.termination_grace_period_seconds)
               and spec.termination_grace_period_seconds >= 0,
               "spec.terminationGracePeriodSeconds: must be non-negative")
    if spec.active_deadline_seconds is not None:
        _check(errs, _is_num(spec.active_deadline_seconds)
               and spec.active_deadline_seconds >= 1,
               "spec.activeDeadlineSeconds: must be >= 1")
    for k, v in (spec.node_selector or {}).items():
        _check(errs, isinstance(k, str) and _valid_qualified_name(k),
               f"spec.nodeSelector: invalid key {k!r}")
        _check(errs, _valid_label_value(v),
               f"spec.nodeSelector[{k}]: invalid value {v!r}")
    vol_names = set()
    for i, vol in enumerate(spec.volumes or []):
        p = f"spec.volumes[{i}]"
        _check(errs, bool(vol.name), f"{p}.name: required")
        if vol.name:
            _check(errs, len(vol.name) <= 63
                   and _DNS1123_LABEL.match(vol.name),
                   f"{p}.name: must be a DNS-1123 label: {vol.name!r}")
            _check(errs, vol.name not in vol_names,
                   f"{p}.name: duplicate {vol.name!r}")
            vol_names.add(vol.name)
    for i, tol in enumerate(spec.tolerations or []):
        p = f"spec.tolerations[{i}]"
        _check(errs, tol.operator in ("", "Exists", "Equal"),
               f"{p}.operator: must be Exists or Equal")
        if tol.operator == "Exists":
            _check(errs, not tol.value,
                   f"{p}.value: must be empty with operator Exists")
        _check(errs, tol.effect in ("", "NoSchedule", "PreferNoSchedule"),
               f"{p}.effect: invalid {tol.effect!r}")
    seen = set()
    host_ports = set()
    for i, c in enumerate(spec.containers):
        p = f"spec.containers[{i}]"
        _check(errs, bool(c.name), f"{p}.name: required")
        if c.name:
            _check(errs, len(c.name) <= 63 and _DNS1123_LABEL.match(c.name),
                   f"{p}.name: must be a DNS-1123 label: {c.name!r}")
            _check(errs, c.name not in seen, f"{p}.name: duplicate {c.name!r}")
            seen.add(c.name)
        _check(errs, bool(c.image), f"{p}.image: required")
        _check(errs, c.image_pull_policy in ("", "Always", "Never",
                                             "IfNotPresent"),
               f"{p}.imagePullPolicy: invalid {c.image_pull_policy!r}")
        if c.resources:
            _validate_resource_list(c.resources.requests, errs,
                                    f"{p}.resources.requests")
            _validate_resource_list(c.resources.limits, errs,
                                    f"{p}.resources.limits")
            _validate_requests_vs_limits(c, errs, p)
        for j, env in enumerate(c.env or []):
            _check(errs, isinstance(env.name, str) and bool(env.name)
                   and _C_IDENTIFIER.match(env.name),
                   f"{p}.env[{j}].name: must be a C identifier: "
                   f"{env.name!r}")
        for j, port in enumerate(c.ports or []):
            pp = f"{p}.ports[{j}]"
            _check(errs, _is_num(port.container_port)
                   and 0 < port.container_port < 65536,
                   f"{pp}.containerPort: out of range")
            _check(errs, _is_num(port.host_port)
                   and 0 <= port.host_port < 65536,
                   f"{pp}.hostPort: out of range")
            if port.name:
                _check(errs, _valid_port_name(port.name),
                       f"{pp}.name: invalid port name {port.name!r}")
            _check(errs, port.protocol in ("", "TCP", "UDP"),
                   f"{pp}.protocol: must be TCP or UDP")
            if port.host_port:
                key = (port.protocol or "TCP", port.host_port)
                _check(errs, key not in host_ports,
                       f"{pp}.hostPort: duplicate {key}")
                host_ports.add(key)
        mount_paths = set()
        for j, m in enumerate(c.volume_mounts or []):
            mp = f"{p}.volumeMounts[{j}]"
            _check(errs, bool(m.name), f"{mp}.name: required")
            _check(errs, not m.name or m.name in vol_names,
                   f"{mp}.name: no volume named {m.name!r}")
            _check(errs, bool(m.mount_path), f"{mp}.mountPath: required")
            _check(errs, m.mount_path not in mount_paths,
                   f"{mp}.mountPath: duplicate {m.mount_path!r}")
            mount_paths.add(m.mount_path)
        _validate_probe(c.liveness_probe, errs, f"{p}.livenessProbe")
        _validate_probe(c.readiness_probe, errs, f"{p}.readinessProbe")
    if errs:
        raise ValidationError(errs)


def validate_pod_update(new: api.Pod, old: api.Pod) -> None:
    """Reference ValidatePodUpdate: the pod spec is immutable except
    containers[*].image (same containers, same order). nodeName changes are
    rejected separately by the registry's binding-only guard."""
    errs: List[str] = []
    ns, os_ = new.spec, old.spec
    if ns is None or os_ is None:
        if (ns is None) != (os_ is None):
            errs.append("spec: may not be added or removed")
        if errs:
            raise ValidationError(errs)
        return
    from kubernetes_tpu.api.serialization import deep_copy
    a, b = deep_copy(ns), deep_copy(os_)
    # normalize the mutable fields + versioned defaults (a v2 client's
    # decode fills restartPolicy/protocol that a v1-stored pod leaves
    # empty — semantically equal specs must compare equal), then demand
    # equality
    for side in (a, b):
        side.restart_policy = side.restart_policy or "Always"
        for c in (side.containers or []):
            c.image = ""
            for port in c.ports or []:
                port.protocol = port.protocol or "TCP"
    b.node_name = a.node_name  # guarded by the binding-only rule instead
    if a != b:
        errs.append("spec: pod updates may not change fields other than "
                    "containers[*].image")
    if errs:
        raise ValidationError(errs)


def validate_node(node: api.Node) -> None:
    errs: List[str] = []
    validate_object_meta(node.metadata, False, errs)
    if node.status:
        _validate_resource_list(node.status.capacity, errs, "status.capacity")
        _validate_resource_list(node.status.allocatable, errs, "status.allocatable")
    if errs:
        raise ValidationError(errs)


def validate_service(svc: api.Service) -> None:
    errs: List[str] = []
    validate_object_meta(svc.metadata, True, errs)
    spec = svc.spec
    if spec is None or not spec.ports:
        errs.append("spec.ports: required")
    else:
        names = set()
        for i, p in enumerate(spec.ports):
            pp = f"spec.ports[{i}]"
            _check(errs, _is_num(p.port) and 0 < p.port < 65536,
                   f"{pp}.port: out of range")
            _check(errs, p.protocol in ("", "TCP", "UDP"),
                   f"{pp}.protocol: must be TCP or UDP")
            if p.name:
                _check(errs, _valid_port_name(p.name),
                       f"{pp}.name: invalid port name {p.name!r}")
                _check(errs, p.name not in names,
                       f"{pp}.name: duplicate {p.name!r}")
                names.add(p.name)
            elif len(spec.ports) > 1:
                errs.append(f"{pp}.name: required when multiple ports")
            if p.node_port:
                _check(errs, _is_num(p.node_port)
                       and 30000 <= p.node_port <= 32767,
                       f"{pp}.nodePort: outside 30000-32767")
        _check(errs, spec.session_affinity in ("", "None", "ClientIP"),
               f"spec.sessionAffinity: invalid {spec.session_affinity!r}")
        _check(errs, spec.type in ("", "ClusterIP", "NodePort",
                                   "LoadBalancer"),
               f"spec.type: invalid {spec.type!r}")
        for k, v in (spec.selector or {}).items():
            _check(errs, isinstance(k, str) and _valid_qualified_name(k),
                   f"spec.selector: invalid key {k!r}")
            _check(errs, _valid_label_value(v),
                   f"spec.selector[{k}]: invalid value {v!r}")
    if errs:
        raise ValidationError(errs)


def validate_binding(binding: api.Binding) -> None:
    """Reference ValidatePodBinding: target kind must be Node (or empty) and
    target name set."""
    errs: List[str] = []
    if binding.target is None:
        errs.append("target: required")
    else:
        _check(errs, binding.target.kind in ("", "Node"),
               f"target.kind: must be Node, got {binding.target.kind!r}")
        _check(errs, bool(binding.target.name), "target.name: required")
    if errs:
        raise ValidationError(errs)


def validate_namespace(ns: api.Namespace) -> None:
    errs: List[str] = []
    validate_object_meta(ns.metadata, False, errs)
    if errs:
        raise ValidationError(errs)


def validate_replication_controller(rc: api.ReplicationController) -> None:
    errs: List[str] = []
    validate_object_meta(rc.metadata, True, errs)
    spec = rc.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, bool(spec.selector), "spec.selector: required")
        if spec.template:
            tpl_labels = (spec.template.metadata.labels or {}) if spec.template.metadata else {}
            for k, v in (spec.selector or {}).items():
                _check(errs, tpl_labels.get(k) == v,
                       f"spec.template.metadata.labels: must satisfy selector ({k}={v})")
    if errs:
        raise ValidationError(errs)


def _selector_matches_template(selector, template, errs):
    """The full LabelSelector (matchLabels + matchExpressions) must select the
    template's labels (reference ValidateDeployment/ValidateJob selector checks)."""
    if selector is None or template is None:
        return
    from kubernetes_tpu.api.labels import selector_from_label_selector
    tpl_labels = (template.metadata.labels or {}) if template.metadata else {}
    try:
        sel = selector_from_label_selector(selector)
    except ValueError as e:
        errs.append(f"spec.selector: {e}")
        return
    _check(errs, sel.matches(tpl_labels),
           "spec.template.metadata.labels: must satisfy spec.selector")


def validate_deployment(d) -> None:
    errs: List[str] = []
    validate_object_meta(d.metadata, True, errs)
    spec = d.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.replicas is not None:
            _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        _selector_matches_template(spec.selector, spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_daemonset(ds) -> None:
    errs: List[str] = []
    validate_object_meta(ds.metadata, True, errs)
    if ds.spec is None:
        errs.append("spec: required")
    else:
        _check(errs, ds.spec.template is not None, "spec.template: required")
        _selector_matches_template(ds.spec.selector, ds.spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_job(job) -> None:
    errs: List[str] = []
    validate_object_meta(job.metadata, True, errs)
    spec = job.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.parallelism is not None:
            _check(errs, spec.parallelism >= 0, "spec.parallelism: must be non-negative")
        if spec.completions is not None:
            _check(errs, spec.completions >= 0, "spec.completions: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        if spec.template and spec.template.spec:
            _check(errs, spec.template.spec.restart_policy in ("Never", "OnFailure", "", None),
                   "spec.template.spec.restartPolicy: must be Never or OnFailure")
    if errs:
        raise ValidationError(errs)


def validate_scheduled_job(sj) -> None:
    errs: List[str] = []
    validate_object_meta(sj.metadata, True, errs)
    spec = sj.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, bool(spec.schedule), "spec.schedule: required")
        if spec.schedule:
            _check(errs, len(spec.schedule.split()) == 5,
                   "spec.schedule: must be a 5-field cron expression")
        _check(errs, spec.concurrency_policy in ("Allow", "Forbid", "Replace"),
               "spec.concurrencyPolicy: must be Allow, Forbid or Replace")
        _check(errs, spec.job_template is not None, "spec.jobTemplate: required")
    if errs:
        raise ValidationError(errs)


def validate_hpa(hpa) -> None:
    errs: List[str] = []
    validate_object_meta(hpa.metadata, True, errs)
    spec = hpa.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, spec.scale_target_ref is not None and bool(spec.scale_target_ref.name),
               "spec.scaleTargetRef.name: required")
        _check(errs, spec.max_replicas >= 1, "spec.maxReplicas: must be >= 1")
        if spec.min_replicas is not None:
            _check(errs, 1 <= spec.min_replicas <= spec.max_replicas,
                   "spec.minReplicas: must be >= 1 and <= maxReplicas")
        if spec.target_cpu_utilization_percentage is not None:
            _check(errs, spec.target_cpu_utilization_percentage >= 1,
                   "spec.targetCPUUtilizationPercentage: must be >= 1")
    if errs:
        raise ValidationError(errs)


def validate_petset(ps) -> None:
    errs: List[str] = []
    validate_object_meta(ps.metadata, True, errs)
    spec = ps.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.replicas is not None:
            _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        _selector_matches_template(spec.selector, spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_resource_quota(rq: api.ResourceQuota) -> None:
    errs: List[str] = []
    validate_object_meta(rq.metadata, True, errs)
    if rq.spec and rq.spec.hard:
        _validate_resource_list(rq.spec.hard, errs, "spec.hard")
    if errs:
        raise ValidationError(errs)


def validate_limit_range(lr: api.LimitRange) -> None:
    errs: List[str] = []
    validate_object_meta(lr.metadata, True, errs)
    for i, item in enumerate((lr.spec.limits if lr.spec else None) or []):
        _check(errs, item.type in ("Pod", "Container"),
               f"spec.limits[{i}].type: must be Pod or Container")
    if errs:
        raise ValidationError(errs)


def validate_secret(s: api.Secret) -> None:
    errs: List[str] = []
    validate_object_meta(s.metadata, True, errs)
    total = sum(len(v) for v in (s.data or {}).values())
    _check(errs, total <= 1024 * 1024, "data: total size must be <= 1MiB")
    if errs:
        raise ValidationError(errs)


VALIDATORS = {
    api.Pod: validate_pod,
    api.Node: validate_node,
    api.Service: validate_service,
    api.Binding: validate_binding,
    api.Namespace: validate_namespace,
    api.ReplicationController: validate_replication_controller,
    api.ResourceQuota: validate_resource_quota,
    api.LimitRange: validate_limit_range,
    api.Secret: validate_secret,
}


def validate(obj) -> None:
    v = VALIDATORS.get(type(obj))
    if v:
        v(obj)
