"""Declarative validation.

Parity target: reference pkg/api/validation/validation.go (3,140 ln) — the
load-bearing subset: object meta (DNS-1123 names, namespace rules), pod spec
(containers present, unique names, resource requests parseable and
non-negative, port ranges), node, service, and binding validation
(ValidatePodBinding)."""

from __future__ import annotations

import re
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import QuantityError, parse_fraction

_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_QUALIFIED_NAME = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_./]*)?[A-Za-z0-9]$")


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def _check(errs, cond, msg):
    if not cond:
        errs.append(msg)


def validate_object_meta(meta: Optional[api.ObjectMeta], namespaced: bool,
                         errs: List[str], prefix: str = "metadata"):
    if meta is None:
        errs.append(f"{prefix}: required")
        return
    name = meta.name
    _check(errs, bool(name or meta.generate_name), f"{prefix}.name: required")
    if name:
        _check(errs, len(name) <= 253 and _DNS1123_SUBDOMAIN.match(name),
               f"{prefix}.name: must be a DNS-1123 subdomain: {name!r}")
    elif meta.generate_name:
        # generateName is a prefix; a random suffix is appended, so a trailing
        # '-' is conventional and must validate (reference ValidateObjectMeta)
        gen = meta.generate_name.rstrip("-")
        _check(errs, len(meta.generate_name) <= 247 and (not gen or _DNS1123_SUBDOMAIN.match(gen)),
               f"{prefix}.generateName: must be a DNS-1123 subdomain prefix: {meta.generate_name!r}")
    if namespaced:
        _check(errs, bool(meta.namespace), f"{prefix}.namespace: required")
        if meta.namespace:
            _check(errs, _DNS1123_LABEL.match(meta.namespace),
                   f"{prefix}.namespace: must be a DNS-1123 label: {meta.namespace!r}")
    else:
        _check(errs, not meta.namespace, f"{prefix}.namespace: not allowed on cluster-scoped object")
    for k in (meta.labels or {}):
        _check(errs, _QUALIFIED_NAME.match(k.rsplit("/", 1)[-1]),
               f"{prefix}.labels: invalid key {k!r}")


def _validate_resource_list(rl, errs, prefix):
    for k, v in (rl or {}).items():
        try:
            # exact fraction: ceil-to-int would round "-100m" up to 0
            q = parse_fraction(v)
            _check(errs, q >= 0, f"{prefix}.{k}: must be non-negative")
        except QuantityError:
            errs.append(f"{prefix}.{k}: invalid quantity {v!r}")


def validate_pod(pod: api.Pod) -> None:
    errs: List[str] = []
    validate_object_meta(pod.metadata, True, errs)
    spec = pod.spec
    if spec is None or not spec.containers:
        errs.append("spec.containers: at least one container required")
    else:
        seen = set()
        for i, c in enumerate(spec.containers):
            p = f"spec.containers[{i}]"
            _check(errs, bool(c.name), f"{p}.name: required")
            _check(errs, c.name not in seen, f"{p}.name: duplicate {c.name!r}")
            seen.add(c.name)
            _check(errs, bool(c.image), f"{p}.image: required")
            if c.resources:
                _validate_resource_list(c.resources.requests, errs, f"{p}.resources.requests")
                _validate_resource_list(c.resources.limits, errs, f"{p}.resources.limits")
            for j, port in enumerate(c.ports or []):
                _check(errs, 0 < port.container_port < 65536,
                       f"{p}.ports[{j}].containerPort: out of range")
                _check(errs, 0 <= port.host_port < 65536,
                       f"{p}.ports[{j}].hostPort: out of range")
    if errs:
        raise ValidationError(errs)


def validate_node(node: api.Node) -> None:
    errs: List[str] = []
    validate_object_meta(node.metadata, False, errs)
    if node.status:
        _validate_resource_list(node.status.capacity, errs, "status.capacity")
        _validate_resource_list(node.status.allocatable, errs, "status.allocatable")
    if errs:
        raise ValidationError(errs)


def validate_service(svc: api.Service) -> None:
    errs: List[str] = []
    validate_object_meta(svc.metadata, True, errs)
    spec = svc.spec
    if spec is None or not spec.ports:
        errs.append("spec.ports: required")
    else:
        for i, p in enumerate(spec.ports):
            _check(errs, 0 < p.port < 65536, f"spec.ports[{i}].port: out of range")
    if errs:
        raise ValidationError(errs)


def validate_binding(binding: api.Binding) -> None:
    """Reference ValidatePodBinding: target kind must be Node (or empty) and
    target name set."""
    errs: List[str] = []
    if binding.target is None:
        errs.append("target: required")
    else:
        _check(errs, binding.target.kind in ("", "Node"),
               f"target.kind: must be Node, got {binding.target.kind!r}")
        _check(errs, bool(binding.target.name), "target.name: required")
    if errs:
        raise ValidationError(errs)


def validate_namespace(ns: api.Namespace) -> None:
    errs: List[str] = []
    validate_object_meta(ns.metadata, False, errs)
    if errs:
        raise ValidationError(errs)


def validate_replication_controller(rc: api.ReplicationController) -> None:
    errs: List[str] = []
    validate_object_meta(rc.metadata, True, errs)
    spec = rc.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, bool(spec.selector), "spec.selector: required")
        if spec.template:
            tpl_labels = (spec.template.metadata.labels or {}) if spec.template.metadata else {}
            for k, v in (spec.selector or {}).items():
                _check(errs, tpl_labels.get(k) == v,
                       f"spec.template.metadata.labels: must satisfy selector ({k}={v})")
    if errs:
        raise ValidationError(errs)


def _selector_matches_template(selector, template, errs):
    """The full LabelSelector (matchLabels + matchExpressions) must select the
    template's labels (reference ValidateDeployment/ValidateJob selector checks)."""
    if selector is None or template is None:
        return
    from kubernetes_tpu.api.labels import selector_from_label_selector
    tpl_labels = (template.metadata.labels or {}) if template.metadata else {}
    try:
        sel = selector_from_label_selector(selector)
    except ValueError as e:
        errs.append(f"spec.selector: {e}")
        return
    _check(errs, sel.matches(tpl_labels),
           "spec.template.metadata.labels: must satisfy spec.selector")


def validate_deployment(d) -> None:
    errs: List[str] = []
    validate_object_meta(d.metadata, True, errs)
    spec = d.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.replicas is not None:
            _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        _selector_matches_template(spec.selector, spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_daemonset(ds) -> None:
    errs: List[str] = []
    validate_object_meta(ds.metadata, True, errs)
    if ds.spec is None:
        errs.append("spec: required")
    else:
        _check(errs, ds.spec.template is not None, "spec.template: required")
        _selector_matches_template(ds.spec.selector, ds.spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_job(job) -> None:
    errs: List[str] = []
    validate_object_meta(job.metadata, True, errs)
    spec = job.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.parallelism is not None:
            _check(errs, spec.parallelism >= 0, "spec.parallelism: must be non-negative")
        if spec.completions is not None:
            _check(errs, spec.completions >= 0, "spec.completions: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        if spec.template and spec.template.spec:
            _check(errs, spec.template.spec.restart_policy in ("Never", "OnFailure", "", None),
                   "spec.template.spec.restartPolicy: must be Never or OnFailure")
    if errs:
        raise ValidationError(errs)


def validate_scheduled_job(sj) -> None:
    errs: List[str] = []
    validate_object_meta(sj.metadata, True, errs)
    spec = sj.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, bool(spec.schedule), "spec.schedule: required")
        if spec.schedule:
            _check(errs, len(spec.schedule.split()) == 5,
                   "spec.schedule: must be a 5-field cron expression")
        _check(errs, spec.concurrency_policy in ("Allow", "Forbid", "Replace"),
               "spec.concurrencyPolicy: must be Allow, Forbid or Replace")
        _check(errs, spec.job_template is not None, "spec.jobTemplate: required")
    if errs:
        raise ValidationError(errs)


def validate_hpa(hpa) -> None:
    errs: List[str] = []
    validate_object_meta(hpa.metadata, True, errs)
    spec = hpa.spec
    if spec is None:
        errs.append("spec: required")
    else:
        _check(errs, spec.scale_target_ref is not None and bool(spec.scale_target_ref.name),
               "spec.scaleTargetRef.name: required")
        _check(errs, spec.max_replicas >= 1, "spec.maxReplicas: must be >= 1")
        if spec.min_replicas is not None:
            _check(errs, 1 <= spec.min_replicas <= spec.max_replicas,
                   "spec.minReplicas: must be >= 1 and <= maxReplicas")
        if spec.target_cpu_utilization_percentage is not None:
            _check(errs, spec.target_cpu_utilization_percentage >= 1,
                   "spec.targetCPUUtilizationPercentage: must be >= 1")
    if errs:
        raise ValidationError(errs)


def validate_petset(ps) -> None:
    errs: List[str] = []
    validate_object_meta(ps.metadata, True, errs)
    spec = ps.spec
    if spec is None:
        errs.append("spec: required")
    else:
        if spec.replicas is not None:
            _check(errs, spec.replicas >= 0, "spec.replicas: must be non-negative")
        _check(errs, spec.template is not None, "spec.template: required")
        _selector_matches_template(spec.selector, spec.template, errs)
    if errs:
        raise ValidationError(errs)


def validate_resource_quota(rq: api.ResourceQuota) -> None:
    errs: List[str] = []
    validate_object_meta(rq.metadata, True, errs)
    if rq.spec and rq.spec.hard:
        _validate_resource_list(rq.spec.hard, errs, "spec.hard")
    if errs:
        raise ValidationError(errs)


def validate_limit_range(lr: api.LimitRange) -> None:
    errs: List[str] = []
    validate_object_meta(lr.metadata, True, errs)
    for i, item in enumerate((lr.spec.limits if lr.spec else None) or []):
        _check(errs, item.type in ("Pod", "Container"),
               f"spec.limits[{i}].type: must be Pod or Container")
    if errs:
        raise ValidationError(errs)


def validate_secret(s: api.Secret) -> None:
    errs: List[str] = []
    validate_object_meta(s.metadata, True, errs)
    total = sum(len(v) for v in (s.data or {}).values())
    _check(errs, total <= 1024 * 1024, "data: total size must be <= 1MiB")
    if errs:
        raise ValidationError(errs)


VALIDATORS = {
    api.Pod: validate_pod,
    api.Node: validate_node,
    api.Service: validate_service,
    api.Binding: validate_binding,
    api.Namespace: validate_namespace,
    api.ReplicationController: validate_replication_controller,
    api.ResourceQuota: validate_resource_quota,
    api.LimitRange: validate_limit_range,
    api.Secret: validate_secret,
}


def validate(obj) -> None:
    v = VALIDATORS.get(type(obj))
    if v:
        v(obj)
