"""Label selector algebra.

Parity target: reference pkg/labels (selector.go) — the matching language used
by every LIST/WATCH, by services/RCs to select pods, and by scheduler
predicates (PodSelectorMatches, ServiceAffinity) and priorities
(SelectorSpread). Supports:

  equality-based:  a=b, a==b, a!=b
  set-based:       a in (v1,v2), a notin (v1), a, !a
  conjunction:     comma-separated requirements

Also the matchLabels/matchExpressions structured form used by NodeAffinity /
PodAffinity (reference pkg/apis/extensions + pkg/api/unversioned
LabelSelector), with operators In, NotIn, Exists, DoesNotExist, Gt, Lt.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Mapping, Optional

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


class SelectorError(ValueError):
    pass


@dataclass(frozen=True)
class Requirement:
    """One term of a selector: key <op> values."""

    key: str
    op: str
    values: tuple = ()

    def __post_init__(self):
        if self.op not in _OPS:
            raise SelectorError(f"unknown operator {self.op!r}")
        object.__setattr__(self, "values", tuple(self.values))

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.op == EXISTS:
            return has
        if self.op == DOES_NOT_EXIST:
            return not has
        if self.op == IN:
            return has and labels[self.key] in self.values
        if self.op == NOT_IN:
            # reference semantics: a key that is absent still satisfies notin
            return not has or labels[self.key] not in self.values
        # Gt/Lt compare integer values; absent key never matches
        if not has:
            return False
        try:
            lhs = int(labels[self.key])
            rhs = int(self.values[0])
        except (ValueError, IndexError):
            return False
        return lhs > rhs if self.op == GT else lhs < rhs


@dataclass(frozen=True)
class Selector:
    """Conjunction of requirements. Empty selector matches everything."""

    requirements: tuple = ()

    def matches(self, labels: Optional[Mapping[str, str]]) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        parts = []
        for r in self.requirements:
            if r.op == EXISTS:
                parts.append(r.key)
            elif r.op == DOES_NOT_EXIST:
                parts.append("!" + r.key)
            elif r.op == IN and len(r.values) == 1:
                parts.append(f"{r.key}={r.values[0]}")
            elif r.op == IN:
                parts.append(f"{r.key} in ({','.join(sorted(r.values))})")
            elif r.op == NOT_IN:
                parts.append(f"{r.key} notin ({','.join(sorted(r.values))})")
            elif r.op == GT and r.values:
                parts.append(f"{r.key}>{r.values[0]}")
            elif r.op == LT and r.values:
                parts.append(f"{r.key}<{r.values[0]}")
            else:
                parts.append(r.key)
        return ",".join(parts)


def everything() -> Selector:
    return Selector(())


def nothing() -> Selector:
    # An impossible requirement; used where the reference returns labels.Nothing()
    return Selector((Requirement("\x00nothing", IN, ()),))


def selector_from_map(m: Optional[Mapping[str, str]]) -> Selector:
    """SelectorFromSet: exact-match on every pair. None -> match nothing
    (mirrors how a nil selector on a service/RC selects no pods)."""
    if m is None:
        return nothing()
    return Selector(tuple(Requirement(k, IN, (v,)) for k, v in sorted(m.items())))


def selector_from_label_selector(ls) -> Selector:
    """Convert the structured LabelSelector form {matchLabels, matchExpressions}
    (dict or api.types.LabelSelector) into a Selector. None -> match nothing,
    empty -> match everything (reference LabelSelectorAsSelector semantics)."""
    if ls is None:
        return nothing()
    if hasattr(ls, "match_labels"):
        match_labels = ls.match_labels or {}
        match_exprs = ls.match_expressions or []
    else:
        match_labels = ls.get("matchLabels") or {}
        match_exprs = ls.get("matchExpressions") or []
    reqs = [Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items())]
    for e in match_exprs:
        if hasattr(e, "key"):
            key, op, values = e.key, e.operator, tuple(e.values or ())
        else:
            key, op, values = e["key"], e["operator"], tuple(e.get("values") or ())
        reqs.append(Requirement(key, op, values))
    return Selector(tuple(reqs))


# --- string parser ("a=b,c in (d,e),!f,cores>4") -----------------------------

def parse_selector(s: Optional[str]) -> Selector:
    """Parse the string selector syntax. Empty/None matches everything."""
    if not s or not s.strip():
        return everything()
    reqs = []
    for clause in _split_clauses(s):
        reqs.append(_parse_clause(clause.strip()))
    return Selector(tuple(reqs))


def _split_clauses(s: str):
    """Split on commas not inside parentheses."""
    depth, start = 0, 0
    for i, ch in enumerate(s):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            yield s[start:i]
            start = i + 1
    yield s[start:]


_CLAUSE_EQ = re.compile(r"^([A-Za-z0-9_./-]+)\s*(==|!=|=)\s*([A-Za-z0-9_.-]*)$")
_CLAUSE_CMP = re.compile(r"^([A-Za-z0-9_./-]+)\s*(>|<)\s*([0-9-]+)$")
_CLAUSE_SET = re.compile(r"^([A-Za-z0-9_./-]+)\s+(in|notin)\s+\(([^)]*)\)$")
_CLAUSE_EXISTS = re.compile(r"^([A-Za-z0-9_./-]+)$")
_CLAUSE_NEXISTS = re.compile(r"^!\s*([A-Za-z0-9_./-]+)$")


def _parse_clause(c: str) -> Requirement:
    if not c:
        raise SelectorError("empty selector clause")
    m = _CLAUSE_SET.match(c)
    if m:
        values = tuple(v.strip() for v in m.group(3).split(","))
        return Requirement(m.group(1), IN if m.group(2) == "in" else NOT_IN, values)
    m = _CLAUSE_EQ.match(c)
    if m:
        op = NOT_IN if m.group(2) == "!=" else IN
        return Requirement(m.group(1), op, (m.group(3),))
    m = _CLAUSE_CMP.match(c)
    if m:
        return Requirement(m.group(1), GT if m.group(2) == ">" else LT, (m.group(3),))
    m = _CLAUSE_NEXISTS.match(c)
    if m:
        return Requirement(m.group(1), DOES_NOT_EXIST)
    m = _CLAUSE_EXISTS.match(c)
    if m:
        return Requirement(m.group(1), EXISTS)
    raise SelectorError(f"couldn't parse selector clause: {c!r}")
