"""Core API types.

Parity target: reference pkg/api/types.go (2,869 ln) / pkg/api/v1/types.go —
the subset that carries the system's behavior: Pod, Node, Service, Endpoints,
ReplicationController, ReplicaSet, Binding, Event, Namespace, PV/PVC, plus the
scheduling-relevant sub-structs (ResourceRequirements, Affinity, Taint,
Toleration, NodeSelector*). Python dataclasses, wire-compatible camelCase JSON
via api.serialization.

Scheduling-critical fields (the tensorization surface, SURVEY §7):
  Pod.spec.node_name        — the binding target (PodSpec.NodeName)
  Pod.spec.containers[].resources.requests — cpu/mem/gpu demands
  Node.status.allocatable   — capacity vector incl. "pods" slot count
  Affinity / Taint / Toleration / node_selector — constraint language
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.serialization import api_field, scheme

# Well-known resource names (reference pkg/api/types.go ResourceName consts)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_GPU = "alpha.kubernetes.io/nvidia-gpu"
RESOURCE_PODS = "pods"

# Pod phases
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

# Condition types / statuses
POD_SCHEDULED = "PodScheduled"
POD_READY = "Ready"
NODE_READY = "Ready"
NODE_OUT_OF_DISK = "OutOfDisk"
NODE_MEMORY_PRESSURE = "MemoryPressure"
CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"

# Taint effects
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"

# Toleration operators
TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"

# Annotation keys (v1.3-era alpha features lived in annotations; kept for
# wire compat — see factory multi-scheduler dispatch, reference factory.go:50)
ANN_SCHEDULER_NAME = "scheduler.alpha.kubernetes.io/name"
ANN_CREATED_BY = "kubernetes.io/created-by"
DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Well-known node label for zone/region topology (reference unversioned well_known_labels)
LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_HOSTNAME = "kubernetes.io/hostname"


@dataclass
class OwnerReference:
    """Identifies an owning object; same-namespace only (reference
    pkg/api/types.go:2324-2342). Drives the garbage collector's cascade."""
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = api_field("uid", default="")
    controller: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    generate_name: str = ""
    namespace: str = ""
    uid: str = api_field("uid", default="")
    resource_version: str = ""
    creation_timestamp: Optional[str] = None
    deletion_timestamp: Optional[str] = None
    labels: Optional[Dict[str, str]] = None
    annotations: Optional[Dict[str, str]] = None
    owner_references: Optional[List["OwnerReference"]] = None


@dataclass
class ListMeta:
    resource_version: str = ""


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = api_field("uid", default="")
    api_version: str = ""
    resource_version: str = ""
    field_path: str = ""


# --- label selector (structured form) ---------------------------------------

@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = ""
    values: Optional[List[str]] = None


@dataclass
class LabelSelector:
    match_labels: Optional[Dict[str, str]] = None
    match_expressions: Optional[List[LabelSelectorRequirement]] = None


# --- node affinity ------------------------------------------------------------

@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = ""  # In, NotIn, Exists, DoesNotExist, Gt, Lt
    values: Optional[List[str]] = None


@dataclass
class NodeSelectorTerm:
    match_expressions: Optional[List[NodeSelectorRequirement]] = None


@dataclass
class NodeSelector:
    node_selector_terms: Optional[List[NodeSelectorTerm]] = None  # ORed


@dataclass
class PreferredSchedulingTerm:
    weight: int = 0  # 1-100
    preference: Optional[NodeSelectorTerm] = None


@dataclass
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[NodeSelector] = None
    preferred_during_scheduling_ignored_during_execution: Optional[List[PreferredSchedulingTerm]] = None


# --- pod (anti-)affinity ------------------------------------------------------

@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: Optional[List[str]] = None  # empty => pod's own namespace
    topology_key: str = ""


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 0
    pod_affinity_term: Optional[PodAffinityTerm] = None


@dataclass
class PodAffinity:
    required_during_scheduling_ignored_during_execution: Optional[List[PodAffinityTerm]] = None
    preferred_during_scheduling_ignored_during_execution: Optional[List[WeightedPodAffinityTerm]] = None


@dataclass
class PodAntiAffinity:
    required_during_scheduling_ignored_during_execution: Optional[List[PodAffinityTerm]] = None
    preferred_during_scheduling_ignored_during_execution: Optional[List[WeightedPodAffinityTerm]] = None


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# --- taints & tolerations -----------------------------------------------------

@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""  # NoSchedule | PreferNoSchedule


@dataclass
class Toleration:
    key: str = ""
    operator: str = ""  # Exists | Equal ("" == Equal)
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        """Reference plugin/pkg/scheduler/algorithm/predicates/predicates.go:949
        (TolerationToleratesTaint)."""
        if self.effect and self.effect != taint.effect:
            return False
        # empty toleration key is a wildcard matching every taint key
        if self.key and self.key != taint.key:
            return False
        op = self.operator or TOLERATION_OP_EQUAL
        if op == TOLERATION_OP_EXISTS:
            return True
        return self.value == taint.value


# --- volumes (scheduling-relevant sources only) ------------------------------

@dataclass
class GCEPersistentDiskVolumeSource:
    pd_name: str = api_field("pdName", default="")
    fs_type: str = ""
    partition: int = 0
    read_only: bool = False


@dataclass
class AWSElasticBlockStoreVolumeSource:
    volume_id: str = api_field("volumeID", default="")
    fs_type: str = ""
    partition: int = 0
    read_only: bool = False


@dataclass
class RBDVolumeSource:
    monitors: Optional[List[str]] = api_field("monitors", default=None)
    image: str = ""
    pool: str = ""
    read_only: bool = False


@dataclass
class ISCSIVolumeSource:
    target_portal: str = ""
    iqn: str = api_field("iqn", default="")
    lun: int = 0
    read_only: bool = False


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""
    read_only: bool = False


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""


@dataclass
class Volume:
    name: str = ""
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = api_field("rbd", default=None)
    iscsi: Optional[ISCSIVolumeSource] = api_field("iscsi", default=None)
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    empty_dir: Optional[EmptyDirVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None


# --- containers & pod ---------------------------------------------------------

@dataclass
class ContainerPort:
    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = api_field("hostIP", default="")


@dataclass
class ResourceRequirements:
    # values are quantity strings ("100m", "500Mi") or numbers
    limits: Optional[Dict[str, str]] = None
    requests: Optional[Dict[str, str]] = None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class SecurityContext:
    privileged: Optional[bool] = None
    run_as_user: Optional[int] = None
    run_as_non_root: Optional[bool] = None
    se_linux_options: Optional[Dict[str, str]] = None


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: Optional[List[str]] = None
    args: Optional[List[str]] = None
    ports: Optional[List[ContainerPort]] = None
    env: Optional[List[EnvVar]] = None
    resources: Optional[ResourceRequirements] = None
    volume_mounts: Optional[List[VolumeMount]] = None
    image_pull_policy: str = ""  # Always | IfNotPresent | Never
    security_context: Optional[SecurityContext] = None
    liveness_probe: Optional["Probe"] = None
    readiness_probe: Optional["Probe"] = None


@dataclass
class ExecAction:
    command: Optional[List[str]] = None


@dataclass
class HTTPGetAction:
    path: str = ""
    port: Optional[object] = None  # int | named port
    host: str = ""
    scheme: str = "HTTP"


@dataclass
class TCPSocketAction:
    port: Optional[object] = None


@dataclass
class Probe:
    """Liveness/readiness probe (reference pkg/api/types.go Probe; handlers in
    pkg/probe/{exec,http,tcp})."""
    exec: Optional[ExecAction] = api_field("exec", default=None)
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    initial_delay_seconds: int = 0
    timeout_seconds: int = 1
    period_seconds: int = 10
    success_threshold: int = 1
    failure_threshold: int = 3


@dataclass
class PodSpec:
    containers: Optional[List[Container]] = None
    volumes: Optional[List[Volume]] = None
    node_selector: Optional[Dict[str, str]] = None
    node_name: str = ""  # set only via the binding subresource
    restart_policy: str = ""
    termination_grace_period_seconds: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    service_account_name: str = ""
    host_network: bool = False
    affinity: Optional[Affinity] = None         # first-class (annotation in v1.3)
    tolerations: Optional[List[Toleration]] = None  # first-class (annotation in v1.3)
    scheduler_name: str = ""                    # first-class (annotation in v1.3)


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    last_probe_time: Optional[str] = None
    last_transition_time: Optional[str] = None


@dataclass
class ContainerStateRunning:
    started_at: Optional[str] = None


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    started_at: Optional[str] = None
    finished_at: Optional[str] = None


@dataclass
class ContainerStateWaiting:
    reason: str = ""
    message: str = ""


@dataclass
class ContainerState:
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[ContainerStateRunning] = None
    terminated: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: Optional[ContainerState] = None
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    container_id: str = api_field("containerID", default="")


@dataclass
class PodStatus:
    phase: str = ""
    conditions: Optional[List[PodCondition]] = None
    message: str = ""
    reason: str = ""
    host_ip: str = api_field("hostIP", default="")
    pod_ip: str = api_field("podIP", default="")
    start_time: Optional[str] = None
    container_statuses: Optional[List[ContainerStatus]] = None


@dataclass
class Pod:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PodSpec] = None
    status: Optional[PodStatus] = None


@dataclass
class PodTemplateSpec:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PodSpec] = None


# --- node ---------------------------------------------------------------------

@dataclass
class NodeSpec:
    pod_cidr: str = api_field("podCIDR", default="")
    provider_id: str = api_field("providerID", default="")
    unschedulable: bool = False
    taints: Optional[List[Taint]] = None  # first-class (annotation in v1.3)


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    last_heartbeat_time: Optional[str] = None
    last_transition_time: Optional[str] = None


@dataclass
class NodeAddress:
    type: str = ""
    address: str = ""


@dataclass
class ContainerImage:
    names: Optional[List[str]] = None
    size_bytes: int = 0


@dataclass
class NodeSystemInfo:
    machine_id: str = api_field("machineID", default="")
    kernel_version: str = ""
    os_image: str = api_field("osImage", default="")
    container_runtime_version: str = ""
    kubelet_version: str = ""


@dataclass
class DaemonEndpoint:
    port: int = 0


@dataclass
class NodeDaemonEndpoints:
    kubelet_endpoint: Optional[DaemonEndpoint] = None


@dataclass
class NodeStatus:
    capacity: Optional[Dict[str, str]] = None
    allocatable: Optional[Dict[str, str]] = None
    phase: str = ""
    conditions: Optional[List[NodeCondition]] = None
    addresses: Optional[List[NodeAddress]] = None
    daemon_endpoints: Optional[NodeDaemonEndpoints] = None
    node_info: Optional[NodeSystemInfo] = None
    images: Optional[List[ContainerImage]] = None


@dataclass
class Node:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[NodeSpec] = None
    status: Optional[NodeStatus] = None


# --- binding (the scheduler's single write) ----------------------------------

@dataclass
class Binding:
    """POST /namespaces/{ns}/bindings — sets pod.spec.node_name iff empty
    (reference pkg/registry/pod/etcd/etcd.go:118-189)."""
    metadata: Optional[ObjectMeta] = None
    target: Optional[ObjectReference] = None


# --- service / endpoints ------------------------------------------------------

@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: Optional[object] = None  # int or str (named port)
    node_port: int = 0


@dataclass
class ServiceSpec:
    ports: Optional[List[ServicePort]] = None
    selector: Optional[Dict[str, str]] = None
    cluster_ip: str = api_field("clusterIP", default="")
    type: str = ""
    session_affinity: str = ""


@dataclass
class LoadBalancerIngress:
    ip: str = api_field("ip", default="")
    hostname: str = ""


@dataclass
class LoadBalancerStatus:
    ingress: Optional[List[LoadBalancerIngress]] = None


@dataclass
class ServiceStatus:
    load_balancer: Optional[LoadBalancerStatus] = None


@dataclass
class Service:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ServiceSpec] = None
    status: Optional[ServiceStatus] = None


@dataclass
class EndpointAddress:
    ip: str = api_field("ip", default="")
    node_name: Optional[str] = None
    target_ref: Optional[ObjectReference] = None


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: Optional[List[EndpointAddress]] = None
    not_ready_addresses: Optional[List[EndpointAddress]] = None
    ports: Optional[List[EndpointPort]] = None


@dataclass
class Endpoints:
    metadata: Optional[ObjectMeta] = None
    subsets: Optional[List[EndpointSubset]] = None


# --- controllers' objects -----------------------------------------------------

@dataclass
class ReplicationControllerSpec:
    replicas: int = 0
    selector: Optional[Dict[str, str]] = None
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    fully_labeled_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicationController:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ReplicationControllerSpec] = None
    status: Optional[ReplicationControllerStatus] = None


@dataclass
class ReplicaSetSpec:
    replicas: int = 0
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    fully_labeled_replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ReplicaSetSpec] = None
    status: Optional[ReplicaSetStatus] = None


# --- namespace / events / pv --------------------------------------------------

@dataclass
class NamespaceSpec:
    finalizers: Optional[List[str]] = None


@dataclass
class NamespaceStatus:
    phase: str = ""


@dataclass
class Namespace:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[NamespaceSpec] = None
    status: Optional[NamespaceStatus] = None


@dataclass
class EventSource:
    component: str = ""
    host: str = ""


@dataclass
class Event:
    metadata: Optional[ObjectMeta] = None
    involved_object: Optional[ObjectReference] = None
    reason: str = ""
    message: str = ""
    source: Optional[EventSource] = None
    first_timestamp: Optional[str] = None
    last_timestamp: Optional[str] = None
    count: int = 0
    type: str = ""


@dataclass
class PersistentVolumeSpec:
    capacity: Optional[Dict[str, str]] = None
    access_modes: Optional[List[str]] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None
    claim_ref: Optional[ObjectReference] = None
    persistent_volume_reclaim_policy: str = ""


@dataclass
class PersistentVolumeStatus:
    phase: str = ""


@dataclass
class PersistentVolume:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PersistentVolumeSpec] = None
    status: Optional[PersistentVolumeStatus] = None


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: Optional[List[str]] = None
    resources: Optional[ResourceRequirements] = None
    volume_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = ""


@dataclass
class PersistentVolumeClaim:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[PersistentVolumeClaimSpec] = None
    status: Optional[PersistentVolumeClaimStatus] = None


# --- config/identity objects (reference pkg/api/types.go Secret/ConfigMap/
# ServiceAccount/LimitRange/ResourceQuota sections) ---------------------------

@dataclass
class LocalObjectReference:
    name: str = ""


@dataclass
class Secret:
    """Reference pkg/api/types.go Secret: opaque named data; values are
    base64 strings on the wire."""
    metadata: Optional[ObjectMeta] = None
    data: Optional[Dict[str, str]] = None
    type: str = "Opaque"


SECRET_TYPE_SERVICE_ACCOUNT_TOKEN = "kubernetes.io/service-account-token"
ANN_SERVICE_ACCOUNT_NAME = "kubernetes.io/service-account.name"
ANN_SERVICE_ACCOUNT_UID = "kubernetes.io/service-account.uid"


@dataclass
class ConfigMap:
    metadata: Optional[ObjectMeta] = None
    data: Optional[Dict[str, str]] = None


@dataclass
class ServiceAccount:
    metadata: Optional[ObjectMeta] = None
    secrets: Optional[List[ObjectReference]] = None
    image_pull_secrets: Optional[List[LocalObjectReference]] = None


@dataclass
class LimitRangeItem:
    """One constraint row (reference LimitRangeItem): type is Pod|Container;
    maps are resource-name -> quantity string."""
    type: str = ""
    max: Optional[Dict[str, str]] = None
    min: Optional[Dict[str, str]] = None
    default: Optional[Dict[str, str]] = None
    default_request: Optional[Dict[str, str]] = None
    max_limit_request_ratio: Optional[Dict[str, str]] = None


@dataclass
class LimitRangeSpec:
    limits: Optional[List[LimitRangeItem]] = None


@dataclass
class LimitRange:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[LimitRangeSpec] = None


@dataclass
class ResourceQuotaSpec:
    hard: Optional[Dict[str, str]] = None
    scopes: Optional[List[str]] = None


@dataclass
class ResourceQuotaStatus:
    hard: Optional[Dict[str, str]] = None
    used: Optional[Dict[str, str]] = None


@dataclass
class ResourceQuota:
    metadata: Optional[ObjectMeta] = None
    spec: Optional[ResourceQuotaSpec] = None
    status: Optional[ResourceQuotaStatus] = None


# --- status (error payloads, reference pkg/api/unversioned Status) -----------

@dataclass
class Status:
    status: str = ""  # Success | Failure
    message: str = ""
    reason: str = ""
    code: int = 0


# --- registration ------------------------------------------------------------

_V1_KINDS = {
    "Pod": Pod,
    "Node": Node,
    "Binding": Binding,
    "Service": Service,
    "Endpoints": Endpoints,
    "ReplicationController": ReplicationController,
    "Namespace": Namespace,
    "Event": Event,
    "PersistentVolume": PersistentVolume,
    "PersistentVolumeClaim": PersistentVolumeClaim,
    "Secret": Secret,
    "ConfigMap": ConfigMap,
    "ServiceAccount": ServiceAccount,
    "LimitRange": LimitRange,
    "ResourceQuota": ResourceQuota,
    "Status": Status,
}
for _kind, _cls in _V1_KINDS.items():
    scheme.add_known_type("v1", _kind, _cls)
scheme.add_known_type("extensions/v1beta1", "ReplicaSet", ReplicaSet)


# --- helpers ------------------------------------------------------------------

def new_pod(name: str, namespace: str = "default", **spec_kwargs) -> Pod:
    return Pod(metadata=ObjectMeta(name=name, namespace=namespace),
               spec=PodSpec(**spec_kwargs), status=PodStatus(phase=POD_PENDING))


def pod_resource_request(pod: Pod) -> Dict[str, int]:
    """Sum container requests into canonical integer units:
    cpu -> milliCPU, memory -> bytes, gpu/pods -> counts.
    Reference schedulercache/node_info.go:158 calculateResource."""
    from kubernetes_tpu.api.quantity import parse_cpu, parse_quantity
    cpu = mem = gpu = 0
    for c in (pod.spec.containers if pod.spec and pod.spec.containers else []):
        req = (c.resources.requests if c.resources and c.resources.requests else {})
        cpu += parse_cpu(req.get(RESOURCE_CPU, 0))
        mem += parse_quantity(req.get(RESOURCE_MEMORY, 0))
        gpu += parse_quantity(req.get(RESOURCE_GPU, 0))
    return {RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem, RESOURCE_GPU: gpu}


def node_allocatable(node: Node) -> Dict[str, int]:
    """Allocatable (falls back to capacity) in canonical integer units.
    Reference NodeStatus.Allocatable semantics."""
    from kubernetes_tpu.api.quantity import parse_cpu, parse_quantity
    st = node.status or NodeStatus()
    src = st.allocatable or st.capacity or {}
    return {
        RESOURCE_CPU: parse_cpu(src.get(RESOURCE_CPU, 0)),
        RESOURCE_MEMORY: parse_quantity(src.get(RESOURCE_MEMORY, 0)),
        RESOURCE_GPU: parse_quantity(src.get(RESOURCE_GPU, 0)),
        RESOURCE_PODS: parse_quantity(src.get(RESOURCE_PODS, 0)),
    }


def get_pod_scheduler_name(pod: Pod) -> str:
    """Multi-scheduler dispatch: spec.scheduler_name, falling back to the
    v1.3 annotation (reference factory.go:426-432 responsibleForPod)."""
    if pod.spec and pod.spec.scheduler_name:
        return pod.spec.scheduler_name
    ann = (pod.metadata.annotations or {}) if pod.metadata else {}
    return ann.get(ANN_SCHEDULER_NAME, DEFAULT_SCHEDULER_NAME)


# field-selector keys each kind supports (reference per-resource
# <Resource>ToSelectableFields + 400 "field label not supported")
SUPPORTED_FIELDS: Dict[str, frozenset] = {
    "Pod": frozenset({"metadata.name", "metadata.namespace", "spec.nodeName",
                      "status.phase"}),
    "Node": frozenset({"metadata.name", "metadata.namespace", "spec.unschedulable"}),
    "Event": frozenset({"metadata.name", "metadata.namespace",
                        "involvedObject.kind", "involvedObject.namespace",
                        "involvedObject.name", "involvedObject.uid",
                        "reason", "source", "type"}),
}
_DEFAULT_FIELDS = frozenset({"metadata.name", "metadata.namespace"})


def supported_fields(kind: str) -> frozenset:
    return SUPPORTED_FIELDS.get(kind, _DEFAULT_FIELDS)


def object_fields(obj) -> Dict[str, str]:
    """Flat field map for field selectors (reference pkg/registry/<r>/strategy.go
    <Resource>ToSelectableFields)."""
    meta = getattr(obj, "metadata", None) or ObjectMeta()
    out = {"metadata.name": meta.name, "metadata.namespace": meta.namespace}
    if isinstance(obj, Pod):
        out["spec.nodeName"] = obj.spec.node_name if obj.spec else ""
        out["status.phase"] = obj.status.phase if obj.status else ""
    elif isinstance(obj, Node):
        out["spec.unschedulable"] = str(bool(obj.spec and obj.spec.unschedulable)).lower()
    elif isinstance(obj, Event):
        io = obj.involved_object or ObjectReference()
        out.update({
            "involvedObject.kind": io.kind,
            "involvedObject.namespace": io.namespace,
            "involvedObject.name": io.name,
            "involvedObject.uid": io.uid,
            "reason": obj.reason,
            "source": (obj.source.component if obj.source else ""),
            "type": obj.type,
        })
    return out
