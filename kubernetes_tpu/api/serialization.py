"""Scheme + codec: typed, versioned, JSON-serializable API objects.

Parity target: reference pkg/runtime (Scheme, codecs) + pkg/conversion.
Instead of Go's reflection-based conversion machinery with generated deep
copies, objects are Python dataclasses and the codec walks type hints:
snake_case attributes <-> camelCase JSON keys (with per-field overrides),
nested dataclasses, lists, and string maps. A kind registry maps
("v1", "Pod") <-> class so untyped JSON can be decoded (runtime.Scheme
AddKnownTypes, pkg/runtime/scheme.go:160).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Optional, Type

_JSON_NAME_KEY = "json"
_camel_cache: dict = {}


def camel(name: str) -> str:
    c = _camel_cache.get(name)
    if c is None:
        parts = name.split("_")
        c = parts[0] + "".join(p.capitalize() for p in parts[1:])
        _camel_cache[name] = c
    return c


def api_field(json_name: Optional[str] = None, default=dataclasses.MISSING,
              default_factory=dataclasses.MISSING):
    """dataclasses.field with an explicit wire name (for irregular casing
    like hostIP, clusterIP, uid)."""
    md = {_JSON_NAME_KEY: json_name} if json_name else {}
    kw = {"metadata": md}
    if default is not dataclasses.MISSING:
        kw["default"] = default
    if default_factory is not dataclasses.MISSING:
        kw["default_factory"] = default_factory
    return dataclasses.field(**kw)


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get(_JSON_NAME_KEY) or camel(f.name)


_hints_cache: dict = {}


def _hints(cls):
    h = _hints_cache.get(cls)
    if h is None:
        h = typing.get_type_hints(cls)
        _hints_cache[cls] = h
    return h


def _strip_optional(t):
    if typing.get_origin(t) is typing.Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return t


def to_dict(obj: Any) -> Any:
    """Serialize a dataclass (or container/scalar) to JSON-ready plain data.
    Fields equal to their default are omitted (omitempty everywhere, which is
    how the reference's versioned types behave on the wire)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            if f.default is not dataclasses.MISSING and v == f.default:
                continue
            if f.default_factory is not dataclasses.MISSING and v == f.default_factory():
                continue
            out[_wire_name(f)] = to_dict(v)
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def from_dict(cls: Type, data: Any) -> Any:
    """Decode plain data into dataclass `cls`, walking type hints. Unknown
    keys are ignored (forward compatibility, like Go JSON decoding)."""
    if data is None:
        return None
    if not dataclasses.is_dataclass(cls):
        return data
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        wire = _wire_name(f)
        if wire not in data:
            continue
        raw = data[wire]
        kwargs[f.name] = _decode_value(_strip_optional(hints[f.name]), raw)
    return cls(**kwargs)


def _decode_value(t, raw):
    if raw is None:
        return None
    origin = typing.get_origin(t)
    if origin in (list, tuple):
        (elem,) = typing.get_args(t) or (Any,)
        elem = _strip_optional(elem)
        seq = [_decode_value(elem, v) for v in raw]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = typing.get_args(t)
        velem = _strip_optional(args[1]) if len(args) == 2 else Any
        return {k: _decode_value(velem, v) for k, v in raw.items()}
    if dataclasses.is_dataclass(t):
        return from_dict(t, raw)
    return raw


# --- kind registry (the Scheme) ----------------------------------------------

class Scheme:
    """Registry of (apiVersion, kind) <-> class, plus encode/decode with
    TypeMeta injection. Mirrors runtime.Scheme (pkg/runtime/scheme.go:43)."""

    def __init__(self):
        self._by_kind: dict = {}
        self._by_cls: dict = {}

    def add_known_type(self, api_version: str, kind: str, cls: Type):
        self._by_kind[(api_version, kind)] = cls
        self._by_cls[cls] = (api_version, kind)

    def kind_for(self, cls_or_obj) -> tuple:
        cls = cls_or_obj if isinstance(cls_or_obj, type) else type(cls_or_obj)
        try:
            return self._by_cls[cls]
        except KeyError:
            raise KeyError(f"type {cls.__name__} not registered in scheme") from None

    def class_for(self, api_version: str, kind: str) -> Type:
        try:
            return self._by_kind[(api_version, kind)]
        except KeyError:
            raise KeyError(f"no kind {kind!r} registered for {api_version!r}") from None

    def encode(self, obj) -> dict:
        d = to_dict(obj)
        api_version, kind = self.kind_for(obj)
        d["apiVersion"] = api_version
        d["kind"] = kind
        return d

    def encode_json(self, obj) -> str:
        return json.dumps(self.encode(obj), separators=(",", ":"))

    def decode(self, data: dict):
        cls = self.class_for(data.get("apiVersion", "v1"), data["kind"])
        return from_dict(cls, data)

    def decode_json(self, s) -> Any:
        return self.decode(json.loads(s))

    def decode_into(self, cls: Type, data: dict):
        return from_dict(cls, data)


scheme = Scheme()  # the default scheme; api.types registers into it on import


def deep_copy(obj):
    """Deep copy via the codec (cheap for our dataclasses; the reference
    generates deep-copy functions per type)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return from_dict(type(obj), to_dict(obj))
    return json.loads(json.dumps(obj))
