"""Resource quantity parsing.

Parity target: reference pkg/api/resource/quantity.go — Kubernetes quantity
strings ("100m" CPU, "500Mi" memory, "1.5Gi", "2e3", "1k") normalised to
integers the scheduler can put in tensors:

  cpu    -> milliCPU (int)   e.g. "100m" -> 100, "2" -> 2000
  memory -> bytes (int)      e.g. "500Mi" -> 524288000, "1G" -> 1e9
  other  -> plain integer counts (gpu, pods)

The TPU decision plane works on int32/float32 tensors, so quantities are
canonicalised at the API boundary exactly once (the reference instead carries
inf.Dec decimals everywhere and converts in the scheduler hot loop —
predicates.go:416 calls Resource.MilliValue() per decision; we pay it once).
"""

from __future__ import annotations

from fractions import Fraction

# Binary (power-of-two) suffixes: Ki, Mi, Gi, Ti, Pi, Ei
_BINARY = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
# Decimal SI suffixes, including milli
_DECIMAL = {
    "m": Fraction(1, 1000),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


class QuantityError(ValueError):
    pass


def _parse(s) -> Fraction:
    """Parse a quantity string into an exact Fraction of base units."""
    if isinstance(s, (int, float)):
        try:
            return Fraction(s).limit_denominator(10**9)
        except (ValueError, OverflowError):
            raise QuantityError(f"invalid quantity: {s!r}") from None
    if not isinstance(s, str) or not s:
        raise QuantityError(f"invalid quantity: {s!r}")
    s = s.strip()
    # exponent form: 2e3, 1.5E2 — but beware suffix 'E' (exa) which only
    # follows a bare number with no digits after; "12E" is exa, "12E3" is exp.
    suffix = ""
    body = s
    for suf in _BINARY:
        if s.endswith(suf):
            suffix = suf
            body = s[: -len(suf)]
            break
    else:
        # single-char decimal suffixes; 'E'/'e' ambiguity with exponent:
        # treat trailing E/e with digits before it and nothing after as exa.
        if s and s[-1] in _DECIMAL and not (s[-1] in "Ee" and _looks_like_exponent(s)):
            suffix = s[-1]
            body = s[:-1]
    try:
        num = Fraction(body)
    except (ValueError, ZeroDivisionError):
        try:
            num = Fraction(float(body)).limit_denominator(10**12)
        except (ValueError, OverflowError):
            raise QuantityError(f"invalid quantity: {s!r}") from None
    if suffix:
        num *= Fraction(_BINARY.get(suffix) or _DECIMAL[suffix])
    return num


def _looks_like_exponent(s: str) -> bool:
    # "12e3" / "1.5E-2" style; a trailing 'E' like "12E" is the exa suffix.
    low = s.lower()
    if "e" not in low:
        return False
    idx = low.rindex("e")
    return idx < len(s) - 1  # digits follow the e


def parse_fraction(s) -> Fraction:
    """Parse to the exact Fraction (for sign/shape checks that must not be
    affected by integer rounding, e.g. validation of '-100m')."""
    return _parse(s)


def parse_quantity(s) -> int:
    """Parse to an integer count (rounding up, like Quantity.Value())."""
    f = _parse(s)
    return int(-(-f.numerator // f.denominator))  # ceil


def parse_cpu(s) -> int:
    """Parse a CPU quantity to milliCPU (Quantity.MilliValue(), rounds up)."""
    f = _parse(s) * 1000
    return int(-(-f.numerator // f.denominator))


def parse_memory(s) -> int:
    """Parse a memory quantity to bytes."""
    return parse_quantity(s)


def format_cpu(milli: int) -> str:
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


def format_memory(b: int) -> str:
    for suf, mult in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if b % mult == 0 and b >= mult:
            return f"{b // mult}{suf}"
    return str(b)
