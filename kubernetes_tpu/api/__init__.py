"""API machinery: typed resources, quantities, selectors, validation, codecs.

Parity target: reference pkg/api (types), pkg/labels, pkg/fields,
pkg/api/resource (Quantity), pkg/api/validation, pkg/runtime (Scheme/codec).
"""

from kubernetes_tpu.api.quantity import parse_quantity, parse_cpu, parse_memory, format_cpu, format_memory
from kubernetes_tpu.api import labels
from kubernetes_tpu.api import fields
