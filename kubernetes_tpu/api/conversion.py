"""Conversion + defaulting: multiple wire versions over one internal form.

Parity target: reference pkg/conversion/converter.go and the Scheme's
versioning machinery (pkg/runtime/scheme.go:43): storage and every component
operate on INTERNAL types; each wire version decodes into its own dataclasses
which convert to/from internal at the API boundary, and versioned decode
applies registered defaulting functions (Scheme.Default) before conversion.

Idiomatic difference: instead of Go's reflection-with-generated-fast-paths,
the default path walks dataclass fields by name (same-named fields copy;
nested dataclasses recurse when the declared destination type differs), and
registered per-(src, dst) functions override it for renamed/restructured
fields — the analogue of Converter.RegisterConversionFunc.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Callable, Dict, Tuple, Type

from kubernetes_tpu.api.serialization import _hints, _strip_optional


class ConversionError(Exception):
    pass


class Converter:
    """(src class, dst class) -> conversion, with a reflective default."""

    def __init__(self):
        self._funcs: Dict[Tuple[Type, Type], Callable] = {}

    def register(self, src: Type, dst: Type, fn: Callable) -> None:
        """fn(src_obj, convert) -> dst_obj, where convert(child, DstCls)
        recursively converts nested values."""
        self._funcs[(src, dst)] = fn

    def register_pair(self, a: Type, b: Type, a_to_b: Callable,
                      b_to_a: Callable) -> None:
        self.register(a, b, a_to_b)
        self.register(b, a, b_to_a)

    def convert(self, obj, dst: Type):
        if obj is None:
            return None
        src = type(obj)
        if src is dst:
            return obj
        fn = self._funcs.get((src, dst))
        if fn is not None:
            return fn(obj, self.convert)
        if dataclasses.is_dataclass(src) and dataclasses.is_dataclass(dst):
            return self._convert_default(obj, dst)
        raise ConversionError(f"no conversion from {src.__name__} "
                              f"to {dst.__name__}")

    def _convert_default(self, obj, dst: Type):
        """Field-by-field copy for same-named fields; nested dataclass
        values recurse into the destination's declared field type (the
        reference's DefaultConvert)."""
        hints = _hints(dst)
        kwargs = {}
        for f in dataclasses.fields(dst):
            if not hasattr(obj, f.name):
                continue
            v = getattr(obj, f.name)
            if v is None:
                continue
            kwargs[f.name] = self._convert_value(v, _strip_optional(hints[f.name]))
        return dst(**kwargs)

    def _convert_value(self, v, want: Type):
        origin = typing.get_origin(want)
        if origin in (list, tuple):
            (elem,) = typing.get_args(want) or (typing.Any,)
            elem = _strip_optional(elem)
            out = [self._convert_value(x, elem) for x in v]
            return tuple(out) if origin is tuple else out
        if origin is dict:
            args = typing.get_args(want)
            velem = _strip_optional(args[1]) if len(args) == 2 else typing.Any
            return {k: self._convert_value(x, velem) for k, x in v.items()}
        if dataclasses.is_dataclass(want) and isinstance(v, type) is False \
                and dataclasses.is_dataclass(type(v)) and type(v) is not want:
            return self.convert(v, want)
        return v


class Defaulter:
    """Per-class defaulting functions applied to freshly-decoded versioned
    objects (Scheme.Default). Functions mutate in place."""

    def __init__(self):
        self._funcs: Dict[Type, Callable] = {}

    def register(self, cls: Type, fn: Callable) -> None:
        self._funcs[cls] = fn

    def default(self, obj) -> None:
        fn = self._funcs.get(type(obj))
        if fn is not None:
            fn(obj)


converter = Converter()   # the process-wide converter (versions register in)
defaulter = Defaulter()
