"""Binary wire codec: the counterpart of the reference's protobuf serializer.

Parity target: reference pkg/runtime/serializer/protobuf/protobuf.go — the
envelope is a 4-byte magic prefix (k8s\\x00) followed by a runtime.Unknown
carrying TypeMeta {apiVersion, kind} and the raw object payload
(protobuf.go:43 prefix, :153 encode, :77 decode). The content type is
application/vnd.kubernetes.protobuf (kubemark clients default to it,
cmd/kubemark/hollow-node.go:65).

The payload here is a self-describing tagged binary encoding of the JSON
object model (varint ints, length-prefixed UTF-8, count-prefixed lists/maps)
rather than schema'd protobuf fields: our dataclass model has no .proto
field numbers, and a self-describing payload keeps the codec total — every
registered kind round-trips with no generated code. Size/speed behavior
matches the reference's motivation: no JSON string escaping/parsing on the
hot path and ~40% smaller than compact JSON on typical Pod objects.
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

MAGIC = b"k8s\x00"
CONTENT_TYPE = "application/vnd.kubernetes.protobuf"

# value type tags
_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3      # zigzag varint
_T_FLOAT = 4    # float64 big-endian
_T_STR = 5      # varint len + utf8
_T_BYTES = 6    # varint len + raw
_T_LIST = 7     # varint count + values
_T_MAP = 8      # varint count + (str key, value) pairs


class BinaryCodecError(ValueError):
    pass


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(data):
            raise BinaryCodecError("truncated varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, pos
        shift += 7
        if shift > 63:
            raise BinaryCodecError("varint too long")


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _encode_value(out: bytearray, v: Any) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        _write_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out.extend(struct.pack(">d", v))
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        out.append(_T_STR)
        _write_varint(out, len(raw))
        out.extend(raw)
    elif isinstance(v, (bytes, bytearray)):
        out.append(_T_BYTES)
        _write_varint(out, len(v))
        out.extend(v)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _write_varint(out, len(v))
        for item in v:
            _encode_value(out, item)
    elif isinstance(v, dict):
        out.append(_T_MAP)
        _write_varint(out, len(v))
        for k, val in v.items():
            if not isinstance(k, str):
                raise BinaryCodecError(f"map key must be str, got {type(k)}")
            raw = k.encode("utf-8")
            _write_varint(out, len(raw))
            out.extend(raw)
            _encode_value(out, val)
    else:
        raise BinaryCodecError(f"unencodable type {type(v)}")


def _decode_value(data: bytes, pos: int) -> Tuple[Any, int]:
    if pos >= len(data):
        raise BinaryCodecError("truncated value")
    tag = data[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        v, pos = _read_varint(data, pos)
        return _unzigzag(v), pos
    if tag == _T_FLOAT:
        if pos + 8 > len(data):
            raise BinaryCodecError("truncated float")
        return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    if tag in (_T_STR, _T_BYTES):
        n, pos = _read_varint(data, pos)
        if pos + n > len(data):
            raise BinaryCodecError("truncated string")
        raw = data[pos:pos + n]
        return (raw.decode("utf-8") if tag == _T_STR else bytes(raw)), pos + n
    if tag == _T_LIST:
        n, pos = _read_varint(data, pos)
        out = []
        for _ in range(n):
            v, pos = _decode_value(data, pos)
            out.append(v)
        return out, pos
    if tag == _T_MAP:
        n, pos = _read_varint(data, pos)
        d = {}
        for _ in range(n):
            klen, pos = _read_varint(data, pos)
            if pos + klen > len(data):
                raise BinaryCodecError("truncated map key")
            k = data[pos:pos + klen].decode("utf-8")
            pos += klen
            d[k], pos = _decode_value(data, pos)
        return d, pos
    raise BinaryCodecError(f"unknown type tag {tag}")


# --- public API ---------------------------------------------------------------

def encode_dict(payload: dict) -> bytes:
    """dict (already carrying apiVersion/kind like the JSON wire form) ->
    magic + envelope(apiVersion, kind, binary payload)."""
    api_version = payload.get("apiVersion", "")
    kind = payload.get("kind", "")
    out = bytearray(MAGIC)
    for s in (api_version, kind):
        raw = s.encode("utf-8")
        _write_varint(out, len(raw))
        out.extend(raw)
    _encode_value(out, payload)
    return bytes(out)


def decode_dict(data: bytes) -> dict:
    if not data.startswith(MAGIC):
        raise BinaryCodecError("missing k8s binary magic prefix")
    pos = len(MAGIC)
    for _ in range(2):  # apiVersion, kind (redundant with payload; validated)
        n, pos = _read_varint(data, pos)
        if pos + n > len(data):
            raise BinaryCodecError("truncated envelope")
        pos += n
    payload, pos = _decode_value(data, pos)
    if not isinstance(payload, dict):
        raise BinaryCodecError("envelope payload is not an object")
    return payload


def is_binary(data: bytes) -> bool:
    return data.startswith(MAGIC)
