"""L7 volume pipeline: plugins + the kubelet-side volume manager.

Parity target: reference pkg/volume/ (plugin drivers) +
pkg/kubelet/volume_manager.go — the other half of the PV story: the
binder controller matches claims to volumes, and THIS code materializes
them on the node. There is no mount(2) privilege or cloud API in this
environment, so the tpu-native analog materializes volumes as real
directories under the pod sandbox:

  - emptyDir      -> a fresh directory, deleted with the pod (the
                     reference's tmpfs/disk emptyDir lifecycle)
  - hostPath      -> the host path itself (validated to exist)
  - PVC           -> resolved claim -> bound PV -> that PV's source:
                     hostPath PVs materialize at their path; EBS/GCE PVs
                     "attach" as a per-volume directory under the
                     manager's attach root with a marker file recording
                     the volume id (the attach/detach bookkeeping the
                     MaxPDVolumeCount predicates meter)
  - EBS/GCE inline sources attach the same way

Exposure convention (documented in ProcessRuntime): each container gets a
mount-root directory `{pod_dir}/mounts/{container}` whose entries mirror
its volumeMounts — entry name = the mountPath with '/' mapped to '_'
(e.g. /data -> data; colliding names are rejected at setup) — each a
symlink to the materialized volume. The process finds it via
$KTPU_MOUNTS. readOnly is recorded in the API and validated, but NOT
enforced at the filesystem layer: without mount namespaces a same-inode
read-only view does not exist, and chmod'ing the shared source would
block legitimate writers. This is a documented divergence from the
reference's mount(2)-level ro.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api


class VolumeError(Exception):
    pass


def _mount_entry_name(mount_path: str) -> str:
    return mount_path.strip("/").replace("/", "_") or "root"


class VolumeManager:
    """Per-kubelet volume lifecycle: setup_pod before the runtime starts
    containers, teardown_pod after it kills them."""

    def __init__(self, root: str, pv_resolver=None):
        """pv_resolver: object with get(resource, name, ns) — normally the
        kubelet's RESTClient; None disables PVC resolution."""
        self.root = root
        self.attach_root = os.path.join(root, "attached")
        os.makedirs(self.attach_root, exist_ok=True)
        self.resolver = pv_resolver
        self._lock = threading.Lock()
        # pod key -> volume name -> materialized path
        self._mounted: Dict[str, Dict[str, str]] = {}
        # pod key -> paths owned by the manager (deleted on teardown)
        self._owned: Dict[str, List[str]] = {}
        # per-pod serialization: setup vs teardown of the SAME pod must not
        # interleave (a teardown slipping between materialization and book
        # registration would find nothing to remove and the dirs would
        # leak). Entries are refcounted so a key's lock object is removed
        # only when its last holder/waiter leaves — popping earlier would
        # let a third caller mint a fresh lock and bypass a live holder.
        self._pod_locks: Dict[str, list] = {}  # key -> [Lock, refcount]

    def _pod_lock(self, key: str) -> threading.Lock:
        with self._lock:
            ent = self._pod_locks.get(key)
            if ent is None:
                ent = self._pod_locks[key] = [threading.Lock(), 0]
            ent[1] += 1
            return ent[0]

    def _release_pod_lock(self, key: str) -> None:
        with self._lock:
            ent = self._pod_locks.get(key)
            if ent is not None:
                ent[1] -= 1
                if ent[1] <= 0:
                    del self._pod_locks[key]

    # -- plugin dispatch -------------------------------------------------------

    def _materialize(self, key: str, pod: api.Pod,
                     vol: api.Volume) -> Tuple[str, bool]:
        """(path, manager_owned) for one volume source."""
        if vol.empty_dir is not None:
            path = os.path.join(self.root, key.replace("/", "_"),
                                "volumes", vol.name)
            os.makedirs(path, exist_ok=True)
            return path, True
        if vol.host_path is not None:
            path = vol.host_path.path
            if not os.path.exists(path):
                raise VolumeError(f"hostPath {path!r} does not exist")
            return path, False
        if vol.aws_elastic_block_store is not None:
            return self._attach("ebs", vol.aws_elastic_block_store.volume_id), True
        if vol.gce_persistent_disk is not None:
            return self._attach("gce", vol.gce_persistent_disk.pd_name), True
        if vol.persistent_volume_claim is not None:
            return self._materialize_pvc(pod, vol)
        raise VolumeError(f"volume {vol.name!r}: no supported source")

    def _attach(self, family: str, volume_id: str) -> str:
        """Fake cloud attach: a stable per-volume directory + marker file
        (the bookkeeping half of the reference's attach/detach controller —
        the data itself is local, there being no cloud)."""
        path = os.path.join(self.attach_root, f"{family}-{volume_id}")
        os.makedirs(path, exist_ok=True)
        marker = os.path.join(path, ".attached")
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write(f"{family}:{volume_id}\n")
        return path

    def _materialize_pvc(self, pod: api.Pod,
                         vol: api.Volume) -> Tuple[str, bool]:
        if self.resolver is None:
            raise VolumeError("PVC volumes need an API resolver")
        ns = pod.metadata.namespace or "default"
        claim = self.resolver.get("persistentvolumeclaims",
                                  vol.persistent_volume_claim.claim_name, ns)
        pv_name = claim.spec.volume_name if claim.spec else ""
        if not pv_name:
            raise VolumeError(
                f"claim {vol.persistent_volume_claim.claim_name!r} is unbound")
        pv = self.resolver.get("persistentvolumes", pv_name)
        src = pv.spec
        if src is None:
            raise VolumeError(f"PV {pv_name!r} has no source")
        if src.host_path is not None:
            if not os.path.exists(src.host_path.path):
                os.makedirs(src.host_path.path, exist_ok=True)
            return src.host_path.path, False
        if src.aws_elastic_block_store is not None:
            return self._attach(
                "ebs", src.aws_elastic_block_store.volume_id), True
        if src.gce_persistent_disk is not None:
            return self._attach("gce", src.gce_persistent_disk.pd_name), True
        raise VolumeError(f"PV {pv_name!r}: no supported source")

    def _in_attach_root(self, path: str) -> bool:
        # separator-suffixed compare: a pod dir like <root>/attached_x
        # (namespace "attached") must NOT match the attach root
        return path == self.attach_root or \
            path.startswith(self.attach_root + os.sep)

    # -- pod lifecycle ---------------------------------------------------------

    def setup_pod(self, pod: api.Pod) -> Dict[str, Dict[str, str]]:
        """Materialize every volume and build the per-container mount view.
        Returns {container name: {mount entry: path}} for the runtime."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        spec = pod.spec
        if spec is None:
            return {}
        # materialization does filesystem work and — for PVCs — apiserver
        # HTTP through the resolver. It runs OUTSIDE the manager-wide lock:
        # one slow claim lookup must not stall every other pod's volume
        # lifecycle on this kubelet (round-5 ADVICE). Only the PER-POD lock
        # is held, serializing setup vs teardown of this one pod; the
        # manager lock guards just the _mounted/_owned books.
        lk = self._pod_lock(key)
        try:
            with lk:
                return self._setup_pod_locked(key, pod, spec)
        finally:
            self._release_pod_lock(key)

    def _setup_pod_locked(self, key: str, pod: api.Pod,
                          spec: api.PodSpec) -> Dict[str, Dict[str, str]]:
        vols: Dict[str, str] = {}
        owned: List[str] = []
        try:
            for vol in spec.volumes or []:
                path, is_owned = self._materialize(key, pod, vol)
                vols[vol.name] = path
                if is_owned:
                    owned.append(path)
            views: Dict[str, Dict[str, str]] = {}
            pod_dir = os.path.join(self.root, key.replace("/", "_"))
            for c in spec.containers or []:
                view_dir = os.path.join(pod_dir, "mounts", c.name)
                os.makedirs(view_dir, exist_ok=True)
                entries: Dict[str, str] = {}
                seen_links: Dict[str, str] = {}
                for m in c.volume_mounts or []:
                    src = vols.get(m.name)
                    if src is None:
                        raise VolumeError(
                            f"container {c.name!r} mounts unknown "
                            f"volume {m.name!r}")
                    entry = _mount_entry_name(m.mount_path)
                    if entry in seen_links:
                        raise VolumeError(
                            f"container {c.name!r}: mount paths "
                            f"{seen_links[entry]!r} and "
                            f"{m.mount_path!r} collide in the view "
                            f"(both map to {entry!r})")
                    seen_links[entry] = m.mount_path
                    link = os.path.join(view_dir, entry)
                    if os.path.islink(link):
                        os.unlink(link)
                    os.symlink(src, link)
                    entries[m.mount_path] = src
                views[c.name] = entries
        except (VolumeError, OSError):
            # rollback: manager-created paths from earlier volumes of
            # this failed setup must not leak (OSError too — a failed
            # symlink/mkdir must not skip it)
            for path in owned:
                if not self._in_attach_root(path):
                    shutil.rmtree(path, ignore_errors=True)
            pod_dir = os.path.join(self.root, key.replace("/", "_"))
            shutil.rmtree(os.path.join(pod_dir, "mounts"),
                          ignore_errors=True)
            raise
        with self._lock:
            self._mounted[key] = vols
            self._owned[key] = owned
        return views

    def teardown_pod(self, key: str) -> None:
        """emptyDir contents die with the pod; attached/hostPath survive
        (the reference reclaims PVs via the recycler, not the kubelet)."""
        lk = self._pod_lock(key)
        try:
            with lk:
                with self._lock:
                    self._mounted.pop(key, None)
                    owned = self._owned.pop(key, [])
                pod_dir = os.path.join(self.root, key.replace("/", "_"))
                for path in owned:
                    if self._in_attach_root(path):
                        continue  # attach bookkeeping outlives the pod
                    shutil.rmtree(path, ignore_errors=True)
                shutil.rmtree(os.path.join(pod_dir, "mounts"),
                              ignore_errors=True)
        finally:
            self._release_pod_lock(key)

    def mounted(self, key: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._mounted.get(key, {}))
