"""L8 service dataplane.

Parity target: reference pkg/proxy/iptables (proxier.go) — the iptables-mode
proxier: consume service + endpoints updates, compile the full NAT ruleset,
apply it atomically in one restore call (proxier.go:640 syncProxyRules with
iptables-restore).
"""

from kubernetes_tpu.proxy.proxier import FakeIptables, Proxier
from kubernetes_tpu.proxy.userspace import LoadBalancerRR, UserspaceProxier
