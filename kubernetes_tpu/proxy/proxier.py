"""iptables-mode proxier: declarative NAT ruleset compiler.

Parity target: reference pkg/proxy/iptables/proxier.go — per service a
KUBE-SVC-<hash> chain jumping probabilistically to per-endpoint KUBE-SEP-
chains (DNAT), rebuilt in full and applied with one restore (:640), driven by
OnServiceUpdate/OnEndpointsUpdate (pkg/proxy/config). The iptables interface
is injectable; FakeIptables (pkg/util/iptables/testing analogue) records the
restored ruleset for tests and hollow nodes."""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient


class FakeIptables:
    """Records rulesets passed to restore_all (fakeiptables.NewFake)."""

    def __init__(self):
        self.rulesets: List[str] = []

    def restore_all(self, ruleset: str):
        self.rulesets.append(ruleset)

    @property
    def current(self) -> str:
        return self.rulesets[-1] if self.rulesets else ""


def _chain_hash(kind: str, svc_key: str, extra: str = "") -> str:
    h = hashlib.sha256(f"{svc_key}{extra}".encode()).hexdigest()[:16].upper()
    return f"KUBE-{kind}-{h}"


class Proxier:
    def __init__(self, client: RESTClient, iptables: Optional[FakeIptables] = None,
                 node_name: str = ""):
        self.client = client
        self.iptables = iptables or FakeIptables()
        self.node_name = node_name
        self.svc_informer = Informer(ListWatch(client, "services"))
        self.ep_informer = Informer(ListWatch(client, "endpoints"))
        # handlers only mark dirty; a single sync loop coalesces bursts into
        # one full recompile (the reference's syncProxyRules rate limiting) —
        # the compiler reads the informer stores directly, which are updated
        # synchronously in event order
        self._dirty = threading.Event()
        self._stop_evt = threading.Event()
        self._sync_thread = None
        mark = lambda *_: self._dirty.set()
        self.svc_informer.add_event_handler(on_add=mark, on_update=mark,
                                            on_delete=mark)
        self.ep_informer.add_event_handler(on_add=mark, on_update=mark,
                                           on_delete=mark)

    # --- the compiler (syncProxyRules, proxier.go:365-640) -------------------

    def sync(self):
        """Rebuild the complete NAT table and apply atomically."""
        services = {_key(s): s for s in self.svc_informer.store.list()}
        endpoints = {_key(e): e for e in self.ep_informer.store.list()}
        lines = ["*nat", ":KUBE-SERVICES - [0:0]", ":KUBE-NODEPORTS - [0:0]"]
        rules = []
        for key, svc in sorted(services.items()):
            spec = svc.spec
            if spec is None or not spec.cluster_ip or not spec.ports:
                continue
            ep = endpoints.get(key)
            affinity = spec.session_affinity == "ClientIP"
            for port in spec.ports:
                proto = (port.protocol or "TCP").lower()
                svc_chain = _chain_hash("SVC", key, f"{port.name}:{port.port}")
                lines.append(f":{svc_chain} - [0:0]")
                rules.append(
                    f"-A KUBE-SERVICES -d {spec.cluster_ip}/32 "
                    f"-p {proto} --dport {port.port} "
                    f"-j {svc_chain}")
                # NodePort/LoadBalancer services also answer on every node's
                # port (proxier.go nodePorts handling; KUBE-NODEPORTS is the
                # last KUBE-SERVICES rule in the reference)
                if port.node_port and spec.type in ("NodePort", "LoadBalancer"):
                    rules.append(
                        f"-A KUBE-NODEPORTS -p {proto} "
                        f"--dport {port.node_port} -j {svc_chain}")
                addrs = _ready_addresses(ep, port.name)
                n = len(addrs)
                sep_chains = []
                for i, (ip, tport) in enumerate(addrs):
                    sep_chain = _chain_hash("SEP", key, f"{ip}:{tport}")
                    sep_chains.append(sep_chain)
                    lines.append(f":{sep_chain} - [0:0]")
                    if affinity:
                        # sticky clients re-match their recorded endpoint
                        # before the probabilistic spread (proxier.go
                        # sessionAffinity recent-module rules)
                        rules.append(
                            f"-A {svc_chain} -m recent --name {sep_chain} "
                            f"--rcheck --seconds 10800 --reap -j {sep_chain}")
                for i, (ip, tport) in enumerate(addrs):
                    sep_chain = sep_chains[i]
                    # probabilistic round-robin like the reference's
                    # --mode random --probability 1/(n-i)
                    prob = (f" -m statistic --mode random "
                            f"--probability {1.0 / (n - i):.5f}"
                            if i < n - 1 else "")
                    rules.append(f"-A {svc_chain}{prob} -j {sep_chain}")
                    remember = (f" -m recent --name {sep_chain} --set"
                                if affinity else "")
                    rules.append(
                        f"-A {sep_chain} -p {proto}{remember} "
                        f"-j DNAT --to-destination {ip}:{tport}")
        # terminal KUBE-SERVICES rule: node-local traffic falls through to the
        # nodeport chain (the reference appends this after every service rule)
        rules.append("-A KUBE-SERVICES -m addrtype --dst-type LOCAL "
                     "-j KUBE-NODEPORTS")
        self.iptables.restore_all("\n".join(lines + rules + ["COMMIT"]) + "\n")

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.svc_informer.run()
        self.ep_informer.run()
        self.svc_informer.wait_for_sync()
        self.ep_informer.wait_for_sync()
        self.sync()

        def loop():
            while not self._stop_evt.is_set():
                if not self._dirty.wait(timeout=0.5):
                    continue
                self._dirty.clear()
                try:
                    self.sync()
                except Exception:
                    import logging
                    logging.getLogger("proxier").exception("sync failed")

        self._sync_thread = threading.Thread(target=loop, name="proxier-sync",
                                             daemon=True)
        self._sync_thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        self.svc_informer.stop()
        self.ep_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def _ready_addresses(ep: Optional[api.Endpoints], port_name: str):
    if ep is None:
        return []
    out = []
    for subset in ep.subsets or []:
        tport = None
        for p in subset.ports or []:
            if not port_name or p.name == port_name:
                tport = p.port
                break
        if tport is None:
            continue
        for addr in subset.addresses or []:
            out.append((addr.ip, tport))
    return out
