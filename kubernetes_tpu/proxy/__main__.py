"""kube-proxy entrypoint: python -m kubernetes_tpu.proxy

Flags bind to KubeProxyConfiguration, served at /configz next to /healthz
and /metrics (reference cmd/kube-proxy). The iptables backend is the
in-process FakeIptables ruleset compiler (no kernel netfilter here); the
compiled ruleset is observable via the debug endpoint for inspection."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apis.componentconfig import KubeProxyConfiguration
from kubernetes_tpu.proxy import FakeIptables, Proxier
from kubernetes_tpu.utils.debugserver import DebugServer, client_from_url


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-proxy")
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--port", type=int, default=10249)
    p.add_argument("--proxy-mode", default="iptables",
                   choices=("iptables", "userspace"))
    p.add_argument("--node-name", default="proxy-node")
    a = p.parse_args(argv)
    cfg = KubeProxyConfiguration(mode=a.proxy_mode)

    client = client_from_url(a.master, qps=100, burst=200)
    if a.proxy_mode == "userspace":
        from kubernetes_tpu.proxy.userspace import UserspaceProxier
        proxier = UserspaceProxier(client)
    else:
        ipt = FakeIptables()
        proxier = Proxier(client, ipt, node_name=a.node_name)
    proxier.start()
    debug = DebugServer(port=a.port,
                        configz={"componentconfig": cfg}).start()
    print(f"kube-proxy debug on http://127.0.0.1:{debug.port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a_: stop.set())
    signal.signal(signal.SIGINT, lambda *a_: stop.set())
    stop.wait()
    proxier.stop()
    debug.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
