"""Userspace proxy mode: a real TCP relay per service port.

Parity target: reference pkg/proxy/userspace — the fallback proxier that
accepts connections itself and copies bytes to a chosen endpoint
(proxysocket.go ProxyTCP), with a round-robin load balancer
(roundrobin.go LoadBalancerRR) supporting ClientIP session affinity.
The reference pairs each proxy socket with iptables REDIRECT rules; here
the relay listens on an ephemeral localhost port per (service, port) and
exposes the mapping, which is what in-process tests and hollow clusters
dial. Unlike the iptables compiler (which only *renders* rules), this mode
actually moves bytes, so it is testable against live socket endpoints.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.proxy.proxier import _ready_addresses

log = logging.getLogger("proxy.userspace")


class LoadBalancerRR:
    """Round-robin endpoint choice with optional ClientIP stickiness
    (reference roundrobin.go: NextEndpoint + affinity map with TTL)."""

    def __init__(self, affinity_ttl: float = 10800.0):
        self._lock = threading.Lock()
        self._endpoints: Dict[str, List[Tuple[str, int]]] = {}
        self._index: Dict[str, int] = {}
        self._affinity: Dict[str, bool] = {}
        self._sticky: Dict[Tuple[str, str], Tuple[Tuple[str, int], float]] = {}
        self.affinity_ttl = affinity_ttl

    def set_endpoints(self, svc_port_key: str, addrs: List[Tuple[str, int]],
                      session_affinity: bool = False):
        with self._lock:
            old = self._endpoints.get(svc_port_key)
            self._endpoints[svc_port_key] = list(addrs)
            self._affinity[svc_port_key] = session_affinity
            if old != addrs:
                self._index[svc_port_key] = 0
                # endpoints changed: stickiness to vanished endpoints is void
                live = set(addrs)
                for k in [k for k in self._sticky if k[0] == svc_port_key]:
                    if self._sticky[k][0] not in live:
                        del self._sticky[k]

    def next_endpoint(self, svc_port_key: str,
                      client_ip: str = "") -> Optional[Tuple[str, int]]:
        now = time.monotonic()
        with self._lock:
            addrs = self._endpoints.get(svc_port_key)
            if not addrs:
                return None
            if self._affinity.get(svc_port_key) and client_ip:
                entry = self._sticky.get((svc_port_key, client_ip))
                if entry is not None and now - entry[1] < self.affinity_ttl:
                    self._sticky[(svc_port_key, client_ip)] = (entry[0], now)
                    return entry[0]
            i = self._index.get(svc_port_key, 0)
            chosen = addrs[i % len(addrs)]
            self._index[svc_port_key] = (i + 1) % len(addrs)
            if self._affinity.get(svc_port_key) and client_ip:
                self._sticky[(svc_port_key, client_ip)] = (chosen, now)
            return chosen

    def endpoint_failed(self, svc_port_key: str, client_ip: str,
                        endpoint: Tuple[str, int]) -> None:
        """A dial to `endpoint` failed: void the caller's sticky entry so the
        retry round-robins to a live endpoint instead of re-pinning the dead
        one for the whole affinity TTL (reference proxysocket.go
        sessionAffinityReset after a failed TryConnectEndpoints dial)."""
        with self._lock:
            entry = self._sticky.get((svc_port_key, client_ip))
            if entry is not None and entry[0] == endpoint:
                del self._sticky[(svc_port_key, client_ip)]


class _ProxySocket:
    """One listening socket relaying to load-balanced endpoints
    (proxysocket.go tcpProxySocket)."""

    def __init__(self, svc_port_key: str, balancer: LoadBalancerRR,
                 host: str = "127.0.0.1", port: int = 0):
        self.key = svc_port_key
        self.balancer = balancer
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name=f"proxy-{svc_port_key}",
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn, addr[0]),
                             daemon=True).start()

    def _serve(self, conn: socket.socket, client_ip: str):
        # retry endpoint dial like the reference's proxySocket retry loop
        backend = None
        for _ in range(4):
            dest = self.balancer.next_endpoint(self.key, client_ip)
            if dest is None:
                break
            try:
                backend = socket.create_connection(dest, timeout=2.0)
                break
            except OSError:
                self.balancer.endpoint_failed(self.key, client_ip, dest)
                continue
        if backend is None:
            conn.close()
            return
        t = threading.Thread(target=_pump, args=(backend, conn), daemon=True)
        t.start()
        _pump(conn, backend)
        t.join(timeout=5)

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


def _pump(src: socket.socket, dst: socket.socket):
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class UserspaceProxier:
    """Watches services/endpoints and maintains one relay socket per
    (service, port). `port_map` maps "ns/name:portname" -> local port
    (standing in for the reference's iptables REDIRECT glue)."""

    def __init__(self, client: RESTClient):
        self.client = client
        self.balancer = LoadBalancerRR()
        self._sockets: Dict[str, _ProxySocket] = {}
        self._node_sockets: Dict[str, _ProxySocket] = {}
        self._lock = threading.Lock()
        self.svc_informer = Informer(ListWatch(client, "services"))
        self.ep_informer = Informer(ListWatch(client, "endpoints"))
        self._dirty = threading.Event()
        self._stop_evt = threading.Event()
        mark = lambda *_: self._dirty.set()
        for inf in (self.svc_informer, self.ep_informer):
            inf.add_event_handler(on_add=mark, on_update=mark, on_delete=mark)

    @property
    def port_map(self) -> Dict[str, int]:
        with self._lock:
            return {k: s.port for k, s in self._sockets.items()}

    def sync(self):
        services = {f"{s.metadata.namespace}/{s.metadata.name}": s
                    for s in self.svc_informer.store.list()}
        endpoints = {f"{e.metadata.namespace}/{e.metadata.name}": e
                     for e in self.ep_informer.store.list()}
        want = {}
        for key, svc in services.items():
            spec = svc.spec
            if spec is None or not spec.ports:
                continue
            for port in spec.ports:
                pkey = f"{key}:{port.name or port.port}"
                # same endpoint-selection semantics as the iptables compiler
                addrs = _ready_addresses(endpoints.get(key), port.name)
                node_port = (port.node_port
                             if spec.type in ("NodePort", "LoadBalancer")
                             else 0)
                want[pkey] = (addrs, spec.session_affinity == "ClientIP",
                              node_port)
        with self._lock:
            for pkey in list(self._sockets):
                if pkey not in want:
                    self._sockets.pop(pkey).stop()
            for pkey in list(self._node_sockets):
                if pkey not in want or not want[pkey][2] \
                        or self._node_sockets[pkey].port != want[pkey][2]:
                    # gone, un-NodePorted, or REALLOCATED: the old listener
                    # must close (a changed nodePort re-opens below)
                    self._node_sockets.pop(pkey).stop()
            for pkey, (addrs, affinity, node_port) in want.items():
                self.balancer.set_endpoints(pkey, addrs, affinity)
                if pkey not in self._sockets:
                    self._sockets[pkey] = _ProxySocket(pkey, self.balancer)
                # NodePort services additionally listen on the actual node
                # port (reference: the userspace proxier's nodePort socket,
                # proxier.go openNodePort) — `curl node:nodePort` is real
                if node_port and pkey not in self._node_sockets:
                    try:
                        self._node_sockets[pkey] = _ProxySocket(
                            pkey, self.balancer, port=node_port)
                    except OSError as e:
                        log.warning("nodePort %d for %s: %s",
                                    node_port, pkey, e)

    def start(self):
        for inf in (self.svc_informer, self.ep_informer):
            inf.run()
        for inf in (self.svc_informer, self.ep_informer):
            inf.wait_for_sync()
        self.sync()

        def loop():
            while not self._stop_evt.is_set():
                if not self._dirty.wait(timeout=0.5):
                    continue
                self._dirty.clear()
                try:
                    self.sync()
                except Exception:
                    log.exception("userspace sync failed")

        threading.Thread(target=loop, name="userspace-proxy-sync",
                         daemon=True).start()
        return self

    def stop(self):
        self._stop_evt.set()
        self.svc_informer.stop()
        self.ep_informer.stop()
        with self._lock:
            for s in self._sockets.values():
                s.stop()
            for s in self._node_sockets.values():
                s.stop()
            self._sockets.clear()
            self._node_sockets.clear()
            self._sockets.clear()


