#!/usr/bin/env python3
"""Benchmark: the north-star config — 30k pending pods onto 5k nodes.

Mirrors the reference's scheduler_perf harness shapes
(test/component/scheduler/perf/util.go:85-131: nodes 4 CPU / 32Gi / 110-pod
cap; pause pods requesting 100m / 500Mi) scaled to BASELINE.json config #5
(30k pods / 5k nodes), with zones, a service for spread scoring, taints and
node labels so the full default-provider predicate/priority surface is
exercised.

Prints ONE JSON line:
  metric       pods scheduled per second through the TPU kernel (steady-state
               device wall-clock, excluding host tensorize + compile)
  vs_baseline  value / 30000 — fraction of the "30k pods in <1s" north star
               (1.0 = north star met; the reference Go scheduler achieves
               ~0.001-0.002 on this workload)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", 5000))
N_PODS = int(os.environ.get("BENCH_PODS", 30000))

METRIC = (f"pods_scheduled_per_sec @ {N_PODS // 1000}k pods / "
          f"{N_NODES // 1000}k nodes (full default-provider kernel)")


def _clear_backends():
    from kubernetes_tpu.utils.platform import clear_backends_compat
    clear_backends_compat()


def build_cluster():
    from kubernetes_tpu.api import types as api

    zones = [f"us-z{i}" for i in range(8)]
    nodes = []
    for i in range(N_NODES):
        labels = {api.LABEL_HOSTNAME: f"node-{i:05d}",
                  api.LABEL_ZONE: zones[i % len(zones)]}
        if i % 10 == 0:
            labels["disk"] = "ssd"
        taints = None
        if i % 50 == 0:
            taints = [api.Taint(key="dedicated", value="infra",
                                effect="NoSchedule")]
        nodes.append(api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}", labels=labels),
            spec=api.NodeSpec(taints=taints),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[api.NodeCondition(type="Ready", status="True")])))

    svc = api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(port=80)]))

    pending = []
    for i in range(N_PODS):
        labels = {"app": "web" if i % 3 == 0 else f"batch-{i % 7}"}
        kw = {}
        if i % 20 == 0:
            kw["node_selector"] = {"disk": "ssd"}
        if i % 50 == 7:
            kw["tolerations"] = [api.Toleration(key="dedicated",
                                                operator="Exists")]
        pending.append(api.Pod(
            metadata=api.ObjectMeta(name=f"pod-{i:05d}", namespace="default",
                                    labels=labels),
            spec=api.PodSpec(
                containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m", "memory": "500Mi"}))],
                **kw)))
    return nodes, pending, [svc]


def _reexec_cpu(reason: str):
    """Re-exec this script in a fresh interpreter pinned to CPU.

    Round-1/2 postmortem: the axon TPU backend can fail setup with
    UNAVAILABLE *or hang indefinitely inside jax.devices()* (tunnel down —
    no exception ever surfaces, so in-process retries are useless and a
    hung thread can't be cleaned up). A fresh process with
    PALLAS_AXON_POOL_IPS removed never registers the TPU platform at all.
    An honest-but-slow CPU number beats a lost round.
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        # already the CPU re-exec — a second hop can only loop forever;
        # report what we have and stop
        fail_json("cpu_fallback", RuntimeError(reason))
        sys.exit(0)
    print(f"bench: falling back to CPU via re-exec: {reason}", file=sys.stderr)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_TPU_ERR"] = reason[:500]
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def run_with_timeout(fn, seconds, stage):
    """Run fn() on a daemon thread; (True, value) or raises on error; a hang
    past `seconds` re-execs the whole bench on CPU (the thread can't be
    killed, but a fresh interpreter can)."""
    import threading

    box = {}

    def target():
        try:
            box["value"] = fn()
        except Exception as e:
            box["err"] = e

    th = threading.Thread(target=target, daemon=True)
    th.start()
    th.join(timeout=seconds)
    if th.is_alive():
        _reexec_cpu(f"{stage} hung for {seconds}s")
    if "err" in box:
        raise box["err"]
    return box["value"]


def init_backend(max_tries=3):
    """Initialize the jax backend; fall back to CPU (fresh process) if the
    TPU errors persistently or hangs."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
        _clear_backends()
        return jax, jax.devices(), os.environ.get("BENCH_TPU_ERR", "forced")

    import jax

    last_err = None
    for attempt in range(max_tries):
        try:
            def probe():
                devs = jax.devices()
                jax.block_until_ready(jax.numpy.zeros(8))
                return devs
            devs = run_with_timeout(
                probe, float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
                "backend init")
            return jax, devs, None
        except Exception as e:  # init failures surface as RuntimeError
            last_err = e
            print(f"bench: backend init attempt {attempt + 1}/{max_tries} "
                  f"failed: {e}", file=sys.stderr)
            try:
                _clear_backends()
            except Exception:
                pass
            if attempt < max_tries - 1:
                time.sleep(min(5 * (2 ** attempt), 30))
    _reexec_cpu(f"TPU init failed {max_tries}x: {last_err!r}")


def fail_json(stage, err, **detail):
    print(json.dumps({
        "metric": METRIC,
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "error": {"stage": stage, "exception": repr(err), **detail},
    }))


def main():
    t_start = time.perf_counter()
    try:
        jax, devs, backend_err = init_backend()
    except Exception as e:
        fail_json("backend_init", e)
        return

    from kubernetes_tpu.ops.kernel import Weights, _schedule_jit, features_of
    from kubernetes_tpu.ops.tensorize import Tensorizer
    from kubernetes_tpu.scheduler.batch import ListServiceLister, make_plugin_args

    nodes, pending, services = build_cluster()
    t_built = time.perf_counter()

    args = make_plugin_args(nodes, service_lister=ListServiceLister(services))
    ct = Tensorizer(plugin_args=args).build(nodes, [], pending)
    t_tensorized = time.perf_counter()
    print(f"bench: tensorized in {t_tensorized - t_built:.1f}s; "
          f"device={devs[0]}", file=sys.stderr)

    import jax.numpy as jnp
    try:
        def upload():
            arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
            jax.block_until_ready(arrays)
            return arrays
        arrays = run_with_timeout(upload, 300, "upload")
    except Exception as e:
        fail_json("upload", e,
                  tensorize_seconds=round(t_tensorized - t_built, 1))
        return
    t_upload = time.perf_counter()

    weights = Weights()
    feats = features_of(ct)
    try:
        def compile_and_run():
            out = _schedule_jit(arrays, ct.n_zones, weights, feats)
            jax.block_until_ready(out)
            return out
        out = run_with_timeout(compile_and_run, 900, "kernel compile")
        t_compiled = time.perf_counter()

        # steady state: same compiled program, fresh run
        runs = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = _schedule_jit(arrays, ct.n_zones, weights, feats)
            jax.block_until_ready(out)
            runs.append(time.perf_counter() - t0)
    except Exception as e:
        fail_json("kernel", e,
                  device=str(devs[0]),
                  tensorize_seconds=round(t_tensorized - t_built, 1),
                  upload_seconds=round(t_upload - t_tensorized, 1))
        return
    best = min(runs)

    import numpy as np
    res = np.asarray(out)[: ct.n_real_pods]
    scheduled = int((res >= 0).sum())

    # correctness guard: no node overcommitted on cpu or pod slots
    assign = res[res >= 0]
    counts = np.bincount(assign, minlength=ct.n_real_nodes)
    assert counts.max() <= 110, f"pod-count overcommit: {counts.max()}"
    cpu_used = counts * 100  # every pod requests 100m
    assert cpu_used.max() <= 4000, f"cpu overcommit: {cpu_used.max()}"

    pods_per_sec = scheduled / best if best > 0 else 0.0
    result = {
        "metric": METRIC,
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 30000.0, 3),
        "detail": {
            "device": str(jax.devices()[0]),
            "scheduled": scheduled,
            "total_pods": ct.n_real_pods,
            "kernel_seconds": round(best, 4),
            "compile_seconds": round(t_compiled - t_upload, 1),
            "tensorize_seconds": round(t_tensorized - t_built, 1),
            "runs": [round(r, 4) for r in runs],
        },
    }
    if backend_err is not None:
        result["detail"]["tpu_fallback"] = backend_err
    print(json.dumps(result))


if __name__ == "__main__":
    main()
