#!/usr/bin/env python3
"""Benchmark: the north-star config — 30k pending pods onto 5k nodes.

Mirrors the reference's scheduler_perf harness shapes
(test/component/scheduler/perf/util.go:85-131: nodes 4 CPU / 32Gi / 110-pod
cap; pause pods requesting 100m / 500Mi) scaled to BASELINE.json config #5
(30k pods / 5k nodes), with zones, a service for spread scoring, taints,
node labels, AND feature-bearing pods (hard/preferred inter-pod
(anti-)affinity, EBS/GCE volumes, host ports) so every optional scan carry
of the default-provider kernel is actually traced and timed — not just the
lean capacity+spread scan (round-3 advisor finding #1).

Timing methodology (round-3 advisor finding #2 — the old min-of-3 with
block_until_ready produced a physically impossible 100µs for a 30k-step
sequential scan on the experimental axon backend):

- every timed run perturbs one input element, so no dispatch is a repeat of
  the previous one;
- the per-run sync barrier is HOST MATERIALIZATION of the [P] assignment
  vector (np.asarray), which cannot complete without the scan having run —
  a non-blocking block_until_ready can't fake it;
- the estimate is the MEDIAN of >= BENCH_RUNS runs, never the min;
- a back-to-back throughput cross-check (K dispatches with distinct inputs,
  all materialized at the end, total/K) bounds the per-run number from
  below: if the median is implausibly smaller, the cross-check wins;
- the whole steady-state loop runs under the hang watchdog
  (run_with_timeout), so a TPU stall after a successful compile cannot
  wedge the bench.

Prints ONE JSON line:
  metric       pods scheduled per second through the TPU kernel (steady-state
               device wall-clock incl. result download, excluding host
               tensorize + compile)
  vs_baseline  value / 30000 — fraction of the "30k pods in <1s" north star
               (1.0 = north star met; the reference Go scheduler achieves
               ~0.001-0.002 on this workload)

Modes (--mode / BENCH_MODE):
  batch (default)  the one-shot 30k/5k solve above
  soak             the kubemark churn soak (observability/soak.py): sustained
                   create/bind/delete at SOAK_RATE pods/s against SOAK_NODES
                   hollow nodes for SOAK_DURATION seconds, steady-state
                   pods/s + scraped e2e p50/p99 + SLO verdicts

Honesty contract (both modes): a run whose scraped
scheduler_stage_timeout_total moved — the stage watchdog fired — is marked
"wedged": true and exits NONZERO, so a BENCH_r05-style 0.0 pods/s can never
masquerade as a measurement again. Error exits are nonzero too.
"""

import json
import os
import sys
import time

_T0 = time.perf_counter()  # module-load mark for the restart probe

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = int(os.environ.get("BENCH_NODES", 5000))
N_PODS = int(os.environ.get("BENCH_PODS", 30000))

METRIC = (f"pods_scheduled_per_sec @ {N_PODS // 1000}k pods / "
          f"{N_NODES // 1000}k nodes (full default-provider kernel)")


def _clear_backends():
    from kubernetes_tpu.utils.platform import clear_backends_compat
    clear_backends_compat()


def build_cluster():
    """(nodes, existing bound pods, pending pods, services).

    Existing pods carry required anti-affinity terms (static symmetry —
    predicates.go:883-921 -> sym carry) and preferred/hard affinity terms
    (reverse score, interpod_affinity.go:86-216 -> te carry), so EVERY
    optional scan carry of the default-provider kernel traces in
    (round-4 verdict #3: BENCH features must all be true)."""
    from kubernetes_tpu.api import types as api

    zones = [f"us-z{i}" for i in range(8)]
    nodes = []
    for i in range(N_NODES):
        labels = {api.LABEL_HOSTNAME: f"node-{i:05d}",
                  api.LABEL_ZONE: zones[i % len(zones)]}
        if i % 10 == 0:
            labels["disk"] = "ssd"
        taints = None
        if i % 50 == 0:
            taints = [api.Taint(key="dedicated", value="infra",
                                effect="NoSchedule")]
        nodes.append(api.Node(
            metadata=api.ObjectMeta(name=f"node-{i:05d}", labels=labels),
            spec=api.NodeSpec(taints=taints),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "32Gi", "pods": "110"},
                conditions=[api.NodeCondition(type="Ready", status="True")])))

    svc = api.Service(
        metadata=api.ObjectMeta(name="web", namespace="default"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(port=80)]))

    # existing bound pods: owners of sym (anti) + te (preferred/hard) terms
    existing = []
    for i in range(max(N_NODES // 5, 8)):
        labels = {"app": "existing"}
        kw = {}
        if i % 4 == 0:
            # required anti-affinity against pending sym-target pods by
            # hostname: forbids those pods from this pod's node (symmetry)
            kw["affinity"] = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"sym": f"s{i % 5}"}),
                        topology_key=api.LABEL_HOSTNAME)]))
        elif i % 4 == 1:
            # preferred zone-affinity toward web pods (reverse te score)
            kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    api.WeightedPodAffinityTerm(
                        weight=3,
                        pod_affinity_term=api.PodAffinityTerm(
                            label_selector=api.LabelSelector(
                                match_labels={"app": "web"}),
                            topology_key=api.LABEL_ZONE))]))
        elif i % 4 == 2:
            # hard affinity owned by an existing pod: reverse-hard score
            # under hardPodAffinityWeight (interpod_affinity.go:120-140)
            kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"app": "web"}),
                        topology_key=api.LABEL_ZONE)]))
        existing.append(api.Pod(
            metadata=api.ObjectMeta(name=f"epod-{i:05d}", namespace="default",
                                    labels=labels),
            spec=api.PodSpec(
                node_name=f"node-{(i * 7) % N_NODES:05d}",
                containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m", "memory": "500Mi"}))],
                **kw)))

    pending = []
    for i in range(N_PODS):
        labels = {"app": "web" if i % 3 == 0 else f"batch-{i % 7}"}
        if i % 617 == 3:
            # targets of the existing pods' anti terms (sym carry exercise)
            labels["sym"] = f"s{i % 5}"
        kw = {}
        if i % 20 == 0:
            kw["node_selector"] = {"disk": "ssd"}
        if i % 50 == 7:
            kw["tolerations"] = [api.Toleration(key="dedicated",
                                                operator="Exists")]
        # feature-bearing pods so the full carry surface is traced+timed
        # (terms dedupe by (namespaces, selector, topology), so a few group
        # shapes repeated over thousands of pods keep the term tables tiny —
        # the realistic workload shape: RC-stamped pods share their terms)
        if i % 500 == 250:
            # hard self-anti-affinity by hostname within a small group
            labels["aa"] = f"g{i % 7}"
            kw["affinity"] = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"aa": f"g{i % 7}"}),
                        topology_key=api.LABEL_HOSTNAME)]))
        elif i % 500 == 0:
            # preferred zone-affinity toward the web service's pods
            kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                preferred_during_scheduling_ignored_during_execution=[
                    api.WeightedPodAffinityTerm(
                        weight=5,
                        pod_affinity_term=api.PodAffinityTerm(
                            label_selector=api.LabelSelector(
                                match_labels={"app": "web"}),
                            topology_key=api.LABEL_ZONE))]))
        elif i % 997 == 1:
            # hard zone-affinity to web pods (satisfied in-batch: pod 0 is
            # app=web and commits first in FIFO order)
            kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"app": "web"}),
                        topology_key=api.LABEL_ZONE)]))
        volumes = None
        if i % 301 == 0:
            volumes = [api.Volume(
                name="data",
                aws_elastic_block_store=api.AWSElasticBlockStoreVolumeSource(
                    volume_id=f"vol-{i % 40}"))]
        elif i % 401 == 0:
            volumes = [api.Volume(
                name="data",
                gce_persistent_disk=api.GCEPersistentDiskVolumeSource(
                    pd_name=f"pd-{i % 40}", read_only=True))]
        ports = None
        if i % 203 == 0:
            ports = [api.ContainerPort(container_port=8080,
                                       host_port=8000 + (i % 100))]
        pending.append(api.Pod(
            metadata=api.ObjectMeta(name=f"pod-{i:05d}", namespace="default",
                                    labels=labels),
            spec=api.PodSpec(
                volumes=volumes,
                containers=[api.Container(
                    name="c", image="pause", ports=ports,
                    resources=api.ResourceRequirements(
                        requests={"cpu": "100m", "memory": "500Mi"}))],
                **kw)))
    return nodes, existing, pending, [svc]


def _reexec_cpu(reason: str):
    """Re-exec this script in a fresh interpreter pinned to CPU.

    Round-1/2 postmortem: the axon TPU backend can fail setup with
    UNAVAILABLE *or hang indefinitely inside jax.devices()* (tunnel down —
    no exception ever surfaces, so in-process retries are useless and a
    hung thread can't be cleaned up). A fresh process with
    PALLAS_AXON_POOL_IPS removed never registers the TPU platform at all.
    An honest-but-slow CPU number beats a lost round.
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        # already the CPU re-exec — a second hop can only loop forever;
        # report what we have and stop (nonzero: this is not a measurement)
        fail_json("cpu_fallback", RuntimeError(reason))
        sys.exit(1)
    print(f"bench: falling back to CPU via re-exec: {reason}", file=sys.stderr)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_TPU_ERR"] = reason[:500]
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def run_with_timeout(fn, seconds, stage):
    """Run fn() on a daemon thread; (True, value) or raises on error; a hang
    past `seconds` re-execs the whole bench on CPU (the thread can't be
    killed, but a fresh interpreter can).

    Deliberately NOT ops/watchdog.run_stages: the scheduler's watchdog
    converts a hang into an in-process error and falls back, but a bench
    process that hit a backend hang is not trustworthy for further timing —
    the only honest recovery is a fresh interpreter pinned to CPU."""
    import threading

    box = {}

    def target():
        try:
            box["value"] = fn()
        except Exception as e:
            box["err"] = e

    th = threading.Thread(target=target, daemon=True)
    th.start()
    th.join(timeout=seconds)
    if th.is_alive():
        _reexec_cpu(f"{stage} hung for {seconds}s")
    if "err" in box:
        raise box["err"]
    return box["value"]


def _enable_cache():
    """Persistent XLA compilation cache: a restarted scheduler (or the
    restart probe below) reuses the compiled scan instead of re-paying the
    ~30s cold compile (round-4 verdict #4)."""
    try:
        from kubernetes_tpu.utils.platform import (
            enable_persistent_compilation_cache,
        )
        return enable_persistent_compilation_cache()
    except Exception as e:  # cache is an optimization, never a blocker
        print(f"bench: compilation cache unavailable: {e}", file=sys.stderr)
        return ""


def init_backend(max_tries=3):
    """Initialize the jax backend; fall back to CPU (fresh process) if the
    TPU errors persistently or hangs."""
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
        _clear_backends()
        _enable_cache()
        return jax, jax.devices(), os.environ.get("BENCH_TPU_ERR", "forced")

    import jax
    _enable_cache()

    last_err = None
    for attempt in range(max_tries):
        try:
            def probe():
                devs = jax.devices()
                jax.block_until_ready(jax.numpy.zeros(8))
                return devs
            devs = run_with_timeout(
                probe, float(os.environ.get("BENCH_INIT_TIMEOUT", 120)),
                "backend init")
            return jax, devs, None
        except Exception as e:  # init failures surface as RuntimeError
            last_err = e
            print(f"bench: backend init attempt {attempt + 1}/{max_tries} "
                  f"failed: {e}", file=sys.stderr)
            try:
                _clear_backends()
            except Exception:
                pass
            if attempt < max_tries - 1:
                time.sleep(min(5 * (2 ** attempt), 30))
    _reexec_cpu(f"TPU init failed {max_tries}x: {last_err!r}")


def pipeline_breakdown():
    """Per-stage timing + compile-cache ledger + stage-timeout counts,
    sourced from the metrics registry — the SAME series every component's
    /metrics serves, so the bench's breakdown and production observability
    cannot drift apart. Stages: tensorize / upload / compile / solve (from
    scheduler_stage_seconds) and bind (from the binding-latency histogram);
    compile-cache events carry the machine-feature fingerprint that keys
    the persistent cache (the round-5 AOT-mismatch failure mode, now a
    visible label)."""
    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

    stages = {}
    for lk, (cnt, total) in METRICS.hist_stats("scheduler_stage_seconds").items():
        stage = dict(lk).get("stage", "?")
        stages[stage] = {"count": int(cnt), "total_seconds": round(total, 4)}
    bind_count, bind_total = 0, 0.0
    for lk, (cnt, total) in METRICS.hist_stats(
            "scheduler_binding_latency_seconds").items():
        bind_count += int(cnt)
        bind_total += total
    if bind_count:
        stages["bind"] = {"count": bind_count,
                          "total_seconds": round(bind_total, 4)}
    cache = []
    for lk, v in sorted(METRICS.counter_series(
            "compile_cache_events_total").items()):
        entry = dict(lk)
        entry["count"] = int(v)
        cache.append(entry)
    out = {"stages": stages, "compile_cache": cache}
    timeouts = {dict(lk).get("stage", "?"): int(v)
                for lk, v in METRICS.counter_series(
                    "scheduler_stage_timeout_total").items()}
    if timeouts:
        out["stage_timeouts"] = timeouts
    return out


def stage_timeout_counts() -> dict:
    """Per-stage scheduler_stage_timeout_total — nonzero means the stage
    watchdog fired somewhere in this run: the run WEDGED and recovered via
    fallback, and its numbers must not pass as a clean measurement."""
    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
    return {dict(lk).get("stage", "?"): int(v)
            for lk, v in METRICS.counter_series(
                "scheduler_stage_timeout_total").items() if v}


def flight_dump(reason, trigger=None):
    """Best-effort forensic bundle (observability/flightrecorder); returns
    the bundle path or None. A failed dump must never mask the error that
    triggered it."""
    try:
        from kubernetes_tpu.observability.flightrecorder import RECORDER
        return RECORDER.dump(reason, trigger=trigger)
    except Exception as e:
        print(f"bench: flight-recorder dump failed: {e!r}", file=sys.stderr)
        return None


def fail_json(stage, err, **detail):
    timeouts = stage_timeout_counts()
    bundle = flight_dump("bench-failed",
                         trigger={"stage": stage, "exception": repr(err)})
    out = {
        "metric": METRIC,
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "wedged": bool(timeouts),
        "error": {"stage": stage, "exception": repr(err), **detail},
        "pipeline": pipeline_breakdown(),
    }
    if bundle:
        out["flight_recorder_bundle"] = bundle
    print(json.dumps(out))


def _finite(q: float):
    """Round a quantile for JSON, mapping NaN (explicit "no samples" from
    Histogram.quantile) and inf (beyond the bucket range) to null — a
    missing measurement must never print as a plausible number."""
    from kubernetes_tpu.utils.metrics import finite_round
    return finite_round(q)


def _max_finite(values):
    """Max over the finite entries (NaN = series never observed); None when
    nothing was observed at all."""
    import math
    finite = [v for v in values if math.isfinite(v)]
    return max(finite) if finite else float("nan")


def run_e2e(n_nodes: int, n_pods: int) -> dict:
    """The LIVE path at full scale: pods created through the API server ->
    informers -> FIFO -> BatchScheduler (incremental mirror) -> device
    kernel -> assume + async bind -> CAS-accepted /bindings writes.

    Reports wall-clock from scheduler start (first FIFO pop) to the last
    CAS-accepted binding — the number BASELINE.md's <1s north star is
    actually about, vs the reference harness shape
    (test/component/scheduler/perf/scheduler_test.go:31, util.go:85-131)."""
    from concurrent.futures import ThreadPoolExecutor

    global N_NODES, N_PODS
    saved = (N_NODES, N_PODS)
    N_NODES, N_PODS = n_nodes, n_pods
    try:
        nodes, existing, pending, services = build_cluster()
    finally:
        N_NODES, N_PODS = saved

    from kubernetes_tpu.api import binary_codec
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.client import RESTClient
    from kubernetes_tpu.scheduler.factory import ConfigFactory
    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

    # teardown must run even when a phase raises: leaked informer/server
    # threads would keep mutating the process-global metrics registry for
    # the rest of the bench run
    server = APIServer().start()
    factory = sched = None
    try:
        # the binary wire codec serves the 30k-pod reflector LISTs several
        # times faster than JSON (round-4 verdict #2: informer sync at 5k/30k
        # must complete, and fast)
        client = RESTClient.for_server(server, qps=50000, burst=50000,
                                       content_type=binary_codec.CONTENT_TYPE)
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=32) as pool:
            list(pool.map(lambda n: client.create("nodes", n), nodes))
            for svc in services:
                client.create("services", svc)
            list(pool.map(lambda p: client.create("pods", p), existing))
            list(pool.map(lambda p: client.create("pods", p), pending))
        t_created = time.perf_counter()

        factory = ConfigFactory(client)
        factory.run(timeout=300)
        # pre-queue: every pending pod in the FIFO before the scheduler runs
        deadline = time.monotonic() + 120
        while (len(factory.pending) < len(pending)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        queued = len(factory.pending)

        sched = factory.create_batch_from_provider(batch_size=4096)
        E2E_HIST = "scheduler_e2e_scheduling_latency_seconds"
        base = METRICS.hist_total(E2E_HIST)
        target = base + len(pending)

        ALG_HIST = "scheduler_scheduling_algorithm_latency_seconds"
        API_HIST = "apiserver_request_seconds"
        alg_snap = METRICS.hist_snapshot(ALG_HIST)
        api_snap = METRICS.hist_snapshot(API_HIST)

        # warm the single program shape (pod_bucket pins every batch to one
        # compile); a dry schedule() has no side effects beyond vocab/jit
        t_warm = time.perf_counter()
        warmup_err = None
        try:
            sched._inc.schedule(pending[: min(4096, len(pending))])
        except Exception as e:
            warmup_err = repr(e)
        warmup_seconds = time.perf_counter() - t_warm

        t_run = time.perf_counter()
        t_last = t_run
        sched.run()
        deadline = time.monotonic() + float(
            os.environ.get("BENCH_E2E_TIMEOUT", 600))
        bound = base
        while time.monotonic() < deadline:
            now_bound = METRICS.hist_total(E2E_HIST)
            if now_bound > bound:
                bound = now_bound
                t_last = time.perf_counter()
                if bound >= target:
                    break
            time.sleep(0.005)
        wall = t_last - t_run
        pods_bound = bound - base
        inc = sched._inc
        out = {
            "nodes": len(nodes), "pods": len(pending), "queued": queued,
            "pods_bound": pods_bound,
            "wall_seconds_first_pop_to_last_bind": round(wall, 3),
            "pods_per_sec": round(pods_bound / wall, 1) if wall > 0 else 0.0,
            "create_seconds": round(t_created - t0, 1),
            "warmup_compile_seconds": round(warmup_seconds, 1),
            "kernel_batches": sched.kernel_batches,
            "kernel_pods": sched.kernel_pods,
            "kernel_failures": sched.kernel_failures,
            "kernel_health": sched.health,
            "bind_p99_seconds": _finite(METRICS.histogram(
                "scheduler_binding_latency_seconds").quantile(0.99)),
            # scheduling-phase p99: per-batch algorithm latency over the
            # drain window (round-4 verdict #8 — the e2e histogram counts
            # queue wait across the whole drain and lands beyond-bucket)
            "scheduling_p99_seconds": _finite(
                METRICS.delta_quantile(ALG_HIST, alg_snap, 0.99)),
            "api_p99_seconds": _finite(_max_finite(
                METRICS.delta_quantile(API_HIST, api_snap, 0.99, verb=v)
                for v in ("GET", "POST", "PUT", "DELETE"))),
            # per-pod e2e latency counts queue wait across the whole drain,
            # so late batches sit behind earlier ones; beyond-bucket -> null
            "e2e_p99_seconds": _finite(
                METRICS.histogram(E2E_HIST).quantile(0.99)),
        }
        if warmup_err:
            out["warmup_error"] = warmup_err
        if inc is not None:
            out["incremental"] = {
                "builds": inc.builds,
                "last_build_seconds": round(inc.last_build_seconds, 3),
                "last_upload_bytes": inc.last_upload_bytes,
                "pod_events": inc.pod_events,
            }
        return out
    finally:
        if sched is not None:
            sched.stop()
        if factory is not None:
            factory.stop()
        server.stop()


def _interleaved_medians(jax_mod, arms, runs: int) -> dict:
    """The shared overhead-gate timing protocol: per round, perturb each
    arm's used0 (distinct inputs defeat dispatch caching) and solve the
    arms back-to-back so they share thermal/scheduler drift; returns
    {label: median seconds}. Both explain_overhead and objective_overhead
    gate on this — a protocol change must apply to both."""
    import statistics

    import numpy as np

    times = {label: [] for label, _arrays, _solve in arms}
    for k in range(1, runs + 1):
        for label, arrays, solve_fn in arms:
            a = dict(arrays)
            a["used0"] = arrays["used0"].at[0, 0].add(np.float32(k) * 1e-3)
            jax_mod.block_until_ready(a["used0"])
            t0 = time.perf_counter()
            solve_fn(a)
            times[label].append(time.perf_counter() - t0)
    return {label: statistics.median(ts) for label, ts in times.items()}


def measure_explain_overhead(jax_mod) -> dict:
    """Device-cost gate for the explain feature (ISSUE 12): at the smoke
    shape (the full-carry-surface fixture batch), solve time with explain
    on must stay within 2% of explain off. Medians over interleaved
    perturbed dispatches; `exceeded` additionally requires a >5 ms absolute
    delta so scheduler-noise on a ~ms solve can't fail a CI run. Also
    asserts on/off assignments are identical (the bit-exact-neutral
    contract, on real dispatch inputs)."""
    import numpy as np
    import jax.numpy as jnp

    from kubernetes_tpu.ops.fixtures import feature_batch
    from kubernetes_tpu.ops.kernel import Weights, _schedule_jit, features_of

    runs = max(3, int(os.environ.get("BENCH_EXPLAIN_RUNS", 15)))
    ct = feature_batch(n_nodes=128, n_pods=64, with_existing=True)
    arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
    jax_mod.block_until_ready(arrays)
    feats, w = features_of(ct), Weights()

    def solve(a, explain):
        out = _schedule_jit(a, ct.n_zones, w, feats, explain)
        return jax_mod.tree_util.tree_map(np.asarray, out)

    base_out = solve(arrays, False)     # warm both compiles
    exp_out = solve(arrays, True)
    if not np.array_equal(base_out[: ct.n_real_pods],
                          exp_out[0][: ct.n_real_pods]):
        return {"error": "explain=on changed assignments at the smoke shape",
                "exceeded": True}

    meds = _interleaved_medians(jax_mod, [
        ("base", arrays, lambda a: solve(a, False)),
        ("explain", arrays, lambda a: solve(a, True)),
    ], runs)
    base_med, exp_med = meds["base"], meds["explain"]
    rel = (exp_med / base_med - 1.0) if base_med > 0 else 0.0
    return {
        "runs": runs,
        "base_seconds": round(base_med, 5),
        "explain_seconds": round(exp_med, 5),
        "relative": round(rel, 4),
        "exceeded": bool(rel > 0.02 and (exp_med - base_med) > 0.005),
    }


def measure_objective_overhead(jax_mod, objective_name: str) -> dict:
    """Device-cost gate for the scheduling-objective modes (ISSUE 13), the
    explain_overhead pattern: at the smoke shape, interleaved perturbed
    dispatches of the default program vs the named objective's program,
    medians compared.  Objective modes ADD traced work (binpack one score
    term, preempt/gang whole carries), so the guard is a runaway-regression
    bound, not a parity bound: exceeded = >25% relative AND >25 ms absolute.

    Also asserts the tentpole's no-cost-when-off contract on real dispatch
    inputs: a disabled ObjectiveConfig lowers to the IDENTICAL program as
    objective=None (same HLO text) and returns bit-identical assignments."""
    import numpy as np
    import jax.numpy as jnp

    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.ops.kernel import Weights, _schedule_jit, features_of
    from kubernetes_tpu.ops.tensorize import Tensorizer
    from kubernetes_tpu.scheduler.batch import make_plugin_args
    from kubernetes_tpu.scheduler.objectives.config import (
        DEFAULT_OBJECTIVE, GANG_LABEL, PRIORITY_ANNOTATION, gang_order,
        get_objective,
    )

    objective = get_objective(objective_name)
    runs = max(3, int(os.environ.get("BENCH_OBJECTIVE_RUNS", 15)))

    nodes = []
    for i in range(128):
        nodes.append(api.Node(
            metadata=api.ObjectMeta(
                name=f"n{i:03d}",
                labels={api.LABEL_HOSTNAME: f"n{i:03d}",
                        api.LABEL_ZONE: f"z{i % 8}"}),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "16Gi", "pods": "32"},
                conditions=[api.NodeCondition(type="Ready",
                                              status="True")])))

    def mk_pod(name, cpu, labels=None, ann=None, node=""):
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace="default",
                                    labels=labels, annotations=ann),
            spec=api.PodSpec(
                node_name=node,
                containers=[api.Container(
                    name="c", image="pause",
                    resources=api.ResourceRequirements(
                        requests={"cpu": cpu, "memory": "256Mi"}))]))

    existing = [mk_pod(f"e{i:03d}", "500m", node=f"n{i % 128:03d}",
                       ann={PRIORITY_ANNOTATION: str(i % 3)})
                for i in range(96)]
    pending = []
    for i in range(64):
        labels, ann = {}, None
        if i % 4 == 0:
            labels[GANG_LABEL] = f"g{i // 16}"
        elif i % 8 == 1:
            ann = {PRIORITY_ANNOTATION: "5"}
        pending.append(mk_pod(f"p{i:03d}", "200m", labels=labels, ann=ann))

    args = make_plugin_args(nodes)
    w = Weights()

    def build(obj, pods):
        ct = Tensorizer(plugin_args=args, objective=obj).build(
            nodes, existing, pods)
        arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
        jax_mod.block_until_ready(arrays)
        return ct, arrays

    ct0, base_arrays = build(None, pending)
    feats = features_of(ct0)

    # the no-cost-when-off contract, on real inputs: disabled config ==
    # objective-free trace, program text and assignments both
    low_none = _schedule_jit.lower(base_arrays, ct0.n_zones, w, feats,
                                   False, None).as_text()
    low_off = _schedule_jit.lower(base_arrays, ct0.n_zones, w, feats,
                                  False, DEFAULT_OBJECTIVE).as_text()
    if low_none != low_off:
        return {"error": "disabled objective changed the traced program",
                "exceeded": True}
    out_none = np.asarray(_schedule_jit(base_arrays, ct0.n_zones, w, feats))
    out_off = np.asarray(_schedule_jit(base_arrays, ct0.n_zones, w, feats,
                                       False, DEFAULT_OBJECTIVE))
    if not np.array_equal(out_none, out_off):
        return {"error": "disabled objective changed assignments",
                "exceeded": True}
    if objective is None or not objective.enabled:
        return {"objective": "default", "identical": True, "exceeded": False}

    obj_pending = pending
    if objective.gang:
        obj_pending, _ = gang_order(pending)
    cto, obj_arrays = build(objective, obj_pending)
    featso = features_of(cto)

    def solve(a, ct, feats_, obj):
        out = _schedule_jit(a, ct.n_zones, w, feats_, False, obj)
        return jax_mod.tree_util.tree_map(np.asarray, out)

    solve(base_arrays, ct0, feats, None)        # warm both compiles
    solve(obj_arrays, cto, featso, objective)

    meds = _interleaved_medians(jax_mod, [
        ("base", base_arrays, lambda a: solve(a, ct0, feats, None)),
        ("obj", obj_arrays, lambda a: solve(a, cto, featso, objective)),
    ], runs)
    base_med, obj_med = meds["base"], meds["obj"]
    rel = (obj_med / base_med - 1.0) if base_med > 0 else 0.0
    return {
        "objective": objective.name,
        "runs": runs,
        "base_seconds": round(base_med, 5),
        "objective_seconds": round(obj_med, 5),
        "relative": round(rel, 4),
        "identical": True,
        "exceeded": bool(rel > 0.25 and (obj_med - base_med) > 0.025),
    }


def solve_and_count(arrays, ct, weights, feats, wave: int):
    """One dispatch, host-materialized (the sync barrier); returns
    (assignments, wave_count) — wave_count 0 on the serial path. The ONE
    place that unpacks the wave return shape for the bench."""
    import numpy as np

    from kubernetes_tpu.ops.kernel import _schedule_jit
    out = _schedule_jit(arrays, ct.n_zones, weights, feats, False, None,
                        wave)
    if wave:
        ret, waves = out
        return np.asarray(ret), int(waves)
    return np.asarray(out), 0


def measure_sharded(jax_mod, ct, weights, feats, wave: int,
                    res_unsharded, n_runs: int) -> dict:
    """The 8x the ROADMAP says is being left on the table: run the SAME
    solve program with inputs laid out over the full ("pods", "nodes")
    device mesh, assert the sharded assignments equal the unsharded ones
    bit-for-bit, and report the sharded steady-state next to the
    single-chip number. Raises on inequality — a sharded solve that
    disagrees is not a speedup, it is a wrong answer."""
    import statistics

    import numpy as np

    from kubernetes_tpu.ops.sharding import make_mesh, shard_arrays

    ndev = len(jax_mod.devices())
    mesh = make_mesh(ndev)

    def solve_np(a):
        return solve_and_count(a, ct, weights, feats, wave)

    with mesh:
        arrays = shard_arrays(mesh, ct.arrays())
        jax_mod.block_until_ready(arrays)
        t0 = time.perf_counter()
        sres, swaves = solve_np(arrays)
        compile_seconds = time.perf_counter() - t0
        if not np.array_equal(sres, res_unsharded):
            diff = int((sres != res_unsharded).sum())
            raise AssertionError(
                f"sharded != unsharded assignments ({diff} rows differ)")
        runs = []
        for k in range(1, n_runs + 1):
            a = dict(arrays)
            a["used0"] = arrays["used0"].at[0, 0].add(np.float32(k) * 1e-3)
            jax_mod.block_until_ready(a["used0"])
            t0 = time.perf_counter()
            solve_np(a)
            runs.append(time.perf_counter() - t0)
    med = statistics.median(runs)
    scheduled = int((res_unsharded[: ct.n_real_pods] >= 0).sum())
    out = {
        "devices": ndev,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "equal": True,
        "kernel_seconds": round(med, 4),
        "pods_per_sec": round(scheduled / med, 1) if med > 0 else 0.0,
        "compile_seconds": round(compile_seconds, 1),
        "runs": [round(r, 4) for r in runs],
    }
    if wave:
        out["wave_count"] = swaves
    return out


def restart_probe() -> None:
    """Fresh-process cold start against the persistent compilation cache:
    module load -> backend -> tensorize -> upload -> (cached) compile ->
    first full schedule. Prints one JSON line the parent embeds as
    detail.restart (round-4 verdict #4: done = < 10s)."""
    try:
        jax, devs, backend_err = init_backend()
        from kubernetes_tpu.ops.kernel import (
            Weights, _schedule_jit, features_of, resolve_wave,
        )
        from kubernetes_tpu.ops.tensorize import Tensorizer
        from kubernetes_tpu.scheduler.batch import (
            ListServiceLister, make_plugin_args,
        )
        import jax.numpy as jnp
        import numpy as np

        nodes, existing, pending, services = build_cluster()
        args = make_plugin_args(nodes,
                                service_lister=ListServiceLister(services))
        from kubernetes_tpu.utils import platform as plat
        ct = Tensorizer(plugin_args=args).build(nodes, existing, pending)
        arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
        t_pre = time.perf_counter()
        cc_before = plat.compile_cache_snapshot()
        # the SAME program the flagship solve compiled (wave by default):
        # the probe proves the persistent cache serves the program the
        # restarted scheduler will actually run
        wv = resolve_wave(None)
        out = _schedule_jit(arrays, ct.n_zones, Weights(),
                            features_of(ct), False, None, wv)
        if wv:
            out = out[0]
        out = np.asarray(out)
        t_done = time.perf_counter()
        cc_event = plat.record_compile_cache_event(cc_before)
        print(json.dumps({
            "restart_to_first_schedule_seconds": round(t_done - _T0, 1),
            "compile_plus_run_seconds": round(t_done - t_pre, 1),
            "compile_cache": cc_event,
            "scheduled": int((out[: ct.n_real_pods] >= 0).sum()),
            "device": str(devs[0]),
        }))
    except Exception as e:
        print(json.dumps({"error": repr(e)}))


def run_restart_probe() -> dict:
    """Spawn the restart probe as a genuinely fresh interpreter."""
    import subprocess
    env = dict(os.environ)
    env["BENCH_RESTART_PROBE"] = "1"
    env["BENCH_E2E"] = "0"
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, timeout=600)
        for line in reversed(res.stdout.decode().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except Exception as e:
        return {"error": repr(e)}
    return {"error": "no probe output"}


def main() -> int:
    t_start = time.perf_counter()
    try:
        jax, devs, backend_err = init_backend()
    except Exception as e:
        fail_json("backend_init", e)
        return 1

    from kubernetes_tpu.ops.kernel import (
        Weights, _schedule_jit, features_of, resolve_wave,
    )
    from kubernetes_tpu.ops.tensorize import Tensorizer
    from kubernetes_tpu.scheduler.batch import ListServiceLister, make_plugin_args

    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

    nodes, existing, pending, services = build_cluster()
    t_built = time.perf_counter()

    args = make_plugin_args(nodes, service_lister=ListServiceLister(services))
    ct = Tensorizer(plugin_args=args).build(nodes, existing, pending)
    t_tensorized = time.perf_counter()
    METRICS.observe("scheduler_stage_seconds", t_tensorized - t_built,
                    stage="tensorize")
    print(f"bench: tensorized in {t_tensorized - t_built:.1f}s; "
          f"device={devs[0]}", file=sys.stderr)

    import jax.numpy as jnp
    try:
        def upload():
            arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
            jax.block_until_ready(arrays)
            return arrays
        arrays = run_with_timeout(upload, 300, "upload")
    except Exception as e:
        fail_json("upload", e,
                  tensorize_seconds=round(t_tensorized - t_built, 1))
        return 1
    t_upload = time.perf_counter()
    METRICS.observe("scheduler_stage_seconds", t_upload - t_tensorized,
                    stage="upload")

    weights = Weights()
    feats = features_of(ct)
    import numpy as np
    n_runs = max(1, int(os.environ.get("BENCH_RUNS", 10)))
    # the flagship solve is the wave-commit program (KTPU_WAVE=0 reverts
    # to the serial per-pod scan); wave_count is the new serial dimension
    wv = resolve_wave(None)

    def solve_np(a):
        return solve_and_count(a, ct, weights, feats, wv)

    def perturb(k):
        """Fresh input dict differing in one element — every dispatch is
        distinct, so no layer can serve a cached previous answer. The value
        tweak (+k µCPU on node 0's existing usage) is far below any predicate
        threshold, so assignments are unchanged."""
        a = dict(arrays)
        a["used0"] = arrays["used0"].at[0, 0].add(np.float32(k) * 1e-3)
        return a

    try:
        from kubernetes_tpu.utils import platform as plat

        def compile_and_run():
            # host materialization is the sync barrier (see module docstring)
            return solve_np(arrays)
        cc_before = plat.compile_cache_snapshot()
        res_full, wave_count = run_with_timeout(
            compile_and_run, 900, "kernel compile")
        t_compiled = time.perf_counter()
        plat.record_compile_cache_event(cc_before)
        METRICS.observe("scheduler_stage_seconds", t_compiled - t_upload,
                        stage="compile")

        def steady_state():
            # per-run: median of n_runs distinct dispatches, each materialized
            runs = []
            for k in range(1, n_runs + 1):
                a = perturb(k)
                jax.block_until_ready(a["used0"])  # perturbation off the clock
                t0 = time.perf_counter()
                solve_np(a)
                dt = time.perf_counter() - t0
                METRICS.observe("scheduler_stage_seconds", dt, stage="solve")
                runs.append(dt)
            # cross-check: K back-to-back distinct dispatches, all
            # materialized at the end; total/K bounds per-dispatch time
            ks = list(range(n_runs + 1, 2 * n_runs + 1))
            ins = [perturb(k) for k in ks]
            jax.block_until_ready([a["used0"] for a in ins])
            t0 = time.perf_counter()
            outs = [_schedule_jit(a, ct.n_zones, weights, feats,
                                  False, None, wv) for a in ins]
            for o in outs:
                jax.tree_util.tree_map(np.asarray, o)
            b2b = (time.perf_counter() - t0) / len(ks)
            return runs, b2b
        runs, b2b = run_with_timeout(steady_state, 600, "steady state")
    except Exception as e:
        fail_json("kernel", e,
                  device=str(devs[0]),
                  tensorize_seconds=round(t_tensorized - t_built, 1),
                  upload_seconds=round(t_upload - t_tensorized, 1))
        return 1

    median = float(np.median(runs))
    # sanity gates: median must be plausible against the back-to-back bound
    # and the run spread must be tame; otherwise the conservative number wins
    suspect = []
    kernel_seconds = median
    if median < 0.5 * b2b:
        suspect.append(f"median {median:.4f}s < half back-to-back {b2b:.4f}s")
        kernel_seconds = b2b
    spread = (max(runs) / min(runs)) if min(runs) > 0 else float("inf")
    if spread > 5.0:
        suspect.append(f"run spread {spread:.1f}x")
        kernel_seconds = max(kernel_seconds, b2b)

    res = res_full[: ct.n_real_pods]
    scheduled = int((res >= 0).sum())

    # sharded side-by-side (ROADMAP item 1's 8x): same program over the
    # full device mesh, bit-equality asserted against the unsharded result
    sharded = None
    if os.environ.get("BENCH_SHARDED", "1") != "0" \
            and len(jax.devices()) > 1:
        try:
            sharded = run_with_timeout(
                lambda: measure_sharded(jax, ct, weights, feats, wv,
                                        res_full, n_runs),
                900, "sharded solve")
        except Exception as e:
            sharded = {"error": repr(e), "equal": False}

    # the live end-to-end path (round-3 verdict #1b): full scale on the
    # device; reduced scale on the CPU fallback so an honest number still
    # lands instead of a multi-hour run
    e2e = None
    if os.environ.get("BENCH_E2E", "1") != "0":
        if os.environ.get("BENCH_FORCE_CPU"):
            e2e_nodes, e2e_pods = 1000, 8000
        else:
            e2e_nodes, e2e_pods = N_NODES, N_PODS
        e2e_nodes = int(os.environ.get("BENCH_E2E_NODES", e2e_nodes))
        e2e_pods = int(os.environ.get("BENCH_E2E_PODS", e2e_pods))
        try:
            e2e = run_with_timeout(
                lambda: run_e2e(e2e_nodes, e2e_pods), 900, "e2e")
        except Exception as e:
            e2e = {"error": repr(e)}

    restart = None
    if os.environ.get("BENCH_RESTART", "1") != "0":
        restart = run_restart_probe()

    explain_overhead = None
    if os.environ.get("BENCH_EXPLAIN", "1") != "0":
        try:
            explain_overhead = run_with_timeout(
                lambda: measure_explain_overhead(jax), 600, "explain overhead")
        except Exception as e:
            # a gate that cannot measure must fail, not silently pass
            # (the error key is checked alongside `exceeded` below)
            explain_overhead = {"error": repr(e)}

    objective_overhead = None
    if os.environ.get("BENCH_OBJECTIVE_GATE", "1") != "0":
        # always runs the disabled-config bit-identity assert; with
        # --objective <mode> additionally medians that mode's program
        # against the default one (interleaved, same smoke shape)
        obj_name = os.environ.get("BENCH_OBJECTIVE", "default")
        try:
            objective_overhead = run_with_timeout(
                lambda: measure_objective_overhead(jax, obj_name), 600,
                "objective overhead")
        except Exception as e:
            objective_overhead = {"error": repr(e)}

    # correctness guard: no node overcommitted on cpu or pod slots
    # (existing bound pods count toward both caps — 100m each)
    assign = res[res >= 0]
    counts = np.bincount(assign, minlength=ct.n_real_nodes).astype(np.int64)
    node_idx = {nm: i for i, nm in enumerate(ct.node_names)}
    for ep in existing:
        counts[node_idx[ep.spec.node_name]] += 1
    assert counts.max() <= 110, f"pod-count overcommit: {counts.max()}"
    cpu_used = counts * 100  # every pod requests 100m
    assert cpu_used.max() <= 4000, f"cpu overcommit: {cpu_used.max()}"

    pods_per_sec = scheduled / kernel_seconds if kernel_seconds > 0 else 0.0
    result = {
        "metric": METRIC,
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_sec / 30000.0, 3),
        "detail": {
            "device": str(jax.devices()[0]),
            "scheduled": scheduled,
            "total_pods": ct.n_real_pods,
            "kernel_seconds": round(kernel_seconds, 4),
            "kernel_seconds_median": round(median, 4),
            "back_to_back_seconds": round(b2b, 4),
            "compile_seconds": round(t_compiled - t_upload, 1),
            "tensorize_seconds": round(t_tensorized - t_built, 1),
            "upload_seconds": round(t_upload - t_tensorized, 1),
            "runs": [round(r, 4) for r in runs],
            "features": {k: (v if isinstance(v, int) and not isinstance(v, bool)
                             else bool(v))
                         for k, v in feats._asdict().items()},
        },
    }
    if wv:
        # the wave-commit telemetry: wave_count IS the kernel's serial
        # dimension now (vs the per-pod scan's P steps)
        result["detail"]["wave_chunk"] = wv
        result["detail"]["wave_count"] = wave_count
        result["detail"]["waves_per_second"] = round(
            wave_count / kernel_seconds, 1) if kernel_seconds > 0 else 0.0
        result["detail"]["scan_step_reduction"] = round(
            ct.n_real_pods / max(wave_count, 1), 1)
    if sharded is not None:
        result["detail"]["sharded"] = sharded
    # per-stage pipeline breakdown + compile-cache ledger, straight from the
    # metrics registry (includes the e2e run's scheduler-recorded stages)
    result["detail"]["pipeline"] = pipeline_breakdown()
    if e2e is not None:
        result["detail"]["e2e"] = e2e
    if restart is not None:
        result["detail"]["restart"] = restart
    if explain_overhead is not None:
        result["detail"]["explain_overhead"] = explain_overhead
    if objective_overhead is not None:
        result["detail"]["objective_overhead"] = objective_overhead
    if suspect:
        result["detail"]["estimator_notes"] = suspect
    if backend_err is not None:
        result["detail"]["tpu_fallback"] = backend_err
    # the honesty gate: a stage watchdog that fired anywhere IN THIS
    # PROCESS (kernel timing, e2e drain) means some number above came from
    # a wedged-then-recovered pipeline — visible flag + nonzero exit. The
    # restart probe runs in its own interpreter, so its registry is not
    # visible here; its error key is checked instead.
    timeouts = stage_timeout_counts()
    result["wedged"] = bool(timeouts)
    if timeouts:
        result["detail"]["stage_timeouts"] = timeouts
    # collect every nonzero-exit cause BEFORE printing, so the forensic
    # bundle below can ride the report for ALL of them — a wave-parity or
    # sharding-equality failure on TPU must be diagnosable from artifacts
    # alone, exactly like a wedge
    fail_reasons = {}
    if timeouts:
        fail_reasons["stage_timeouts"] = timeouts
    if restart is not None and restart.get("error"):
        # a failed restart probe is not a clean measurement
        fail_reasons["restart"] = restart["error"]
    if explain_overhead is not None and (explain_overhead.get("exceeded")
                                         or explain_overhead.get("error")):
        # explain must stay within 2% — and must be measurable
        fail_reasons["explain_overhead"] = explain_overhead
    if objective_overhead is not None and (
            objective_overhead.get("exceeded")
            or objective_overhead.get("error")):
        # objective modes: bounded overhead + exact off-identity
        fail_reasons["objective_overhead"] = objective_overhead
    if sharded is not None and not sharded.get("equal"):
        # a sharded solve that disagrees (or couldn't run) is not a number
        fail_reasons["sharded"] = sharded.get("error", "not equal")
    if fail_reasons:
        bundle = flight_dump(
            "bench-wedged" if timeouts else "bench-nonzero-exit",
            trigger={"reasons": {k: repr(v)[:500]
                                 for k, v in fail_reasons.items()}})
        if bundle:
            result["flight_recorder_bundle"] = bundle
    print(json.dumps(result))
    return 1 if fail_reasons else 0


def main_soak() -> int:
    """The churn soak (ROADMAP item 2's steady-state metric): sustained
    create/bind/delete against kubemark hollow nodes, SLIs scraped from the
    component's own /metrics, SLO burn-rate verdicts inline. Scale via
    SOAK_NODES / SOAK_RATE / SOAK_DURATION / SOAK_SCRAPE_PERIOD;
    BENCH_SOAK_HANG_STAGE seeds a kernel-stage hang (the wedge-detection
    proof: the run must end wedged+nonzero, never hung, never 0.0-as-data).
    """
    from kubernetes_tpu.observability.soak import SoakConfig, run_soak

    cfg = SoakConfig(
        num_nodes=int(os.environ.get("SOAK_NODES", 1000)),
        create_rate=float(os.environ.get("SOAK_RATE", 500)),
        duration_seconds=float(os.environ.get("SOAK_DURATION", 60)),
        scrape_period=float(os.environ.get("SOAK_SCRAPE_PERIOD", 2)),
        batch_size=int(os.environ.get("SOAK_BATCH", 256)),
        microbatch_ms=float(os.environ.get("SOAK_MICROBATCH_MS", 0)),
        hang_stage=os.environ.get("BENCH_SOAK_HANG_STAGE", ""),
        scenario=os.environ.get("SOAK_SCENARIO", "churn"),
        gang_size=int(os.environ.get("SOAK_GANG_SIZE", 3)),
        preempt_every=int(os.environ.get("SOAK_PREEMPT_EVERY", 8)),
        objective=os.environ.get("SOAK_OBJECTIVE", ""),
        apiservers=int(os.environ.get("SOAK_APISERVERS", 2)),
        store_members=int(os.environ.get("SOAK_STORE_MEMBERS", 3)),
        kill_at_fraction=float(os.environ.get("SOAK_KILL_AT", 0.4)),
    )
    report = run_soak(cfg)
    steady = report.get("steady_state") or {}
    pods_per_sec = steady.get("pods_per_sec") or 0.0
    result = {
        "metric": (f"steady_state pods_scheduled_per_sec @ "
                   f"{cfg.create_rate:g}/s churn on {cfg.num_nodes} "
                   f"hollow nodes for {cfg.duration_seconds:g}s"),
        "value": round(pods_per_sec, 1),
        "unit": "pods/s",
        # the soak's baseline is keeping up with the offered churn rate
        "vs_baseline": round(pods_per_sec / cfg.create_rate, 3)
        if cfg.create_rate else 0.0,
        "wedged": bool(report.get("wedged")),
        "detail": report,
    }
    # surface the black-box bundle at top level too: artifact consumers
    # (check_soak, the next postmortem) shouldn't have to know the soak
    # report's internals to find it
    if report.get("flight_recorder_bundle"):
        result["flight_recorder_bundle"] = report["flight_recorder_bundle"]
    print(json.dumps(result))
    return 1 if report.get("wedged") or report.get("error") else 0


def parse_mode(argv) -> str:
    import argparse
    p = argparse.ArgumentParser(prog="bench.py")
    p.add_argument("--mode", choices=("batch", "soak"),
                   default=os.environ.get("BENCH_MODE", "batch"))
    p.add_argument(
        "--objective",
        choices=("default", "binpack", "preempt", "gang", "gang_preempt"),
        default=os.environ.get("BENCH_OBJECTIVE", "default"),
        help="scheduling-objective config for the overhead gate (batch "
             "mode: detail.objective_overhead) or the soak's scheduler "
             "(soak mode)")
    p.add_argument(
        "--scenario",
        choices=("churn", "gang_churn", "leader_kill"),
        default=os.environ.get("SOAK_SCENARIO", "churn"),
        help="soak-mode scenario: plain churn, gang churn under "
             "gang_preempt, or leader_kill — churn against a 3-member "
             "replicated store + 2 apiservers behind the discovery proxy "
             "with the storage leader and an apiserver killed mid-run "
             "(report gains a `failover` block; lost acked bindings wedge "
             "the run)")
    args = p.parse_args(argv)
    os.environ["SOAK_SCENARIO"] = args.scenario
    # downstream code reads these through the env (the soak subprocess and
    # the gate helper both live behind run_with_timeout seams)
    os.environ["BENCH_OBJECTIVE"] = args.objective
    if args.mode == "soak" and args.objective != "default" \
            and not os.environ.get("SOAK_OBJECTIVE"):
        os.environ["SOAK_OBJECTIVE"] = args.objective
    return args.mode


if __name__ == "__main__":
    if os.environ.get("BENCH_RESTART_PROBE"):
        restart_probe()
        sys.exit(0)
    mode = parse_mode(sys.argv[1:])
    try:
        rc = main_soak() if mode == "soak" else main()
    except Exception as e:  # incl. assertion failures in the guards
        # ANY nonzero exit ships its black box: fail_json dumps a
        # flight-recorder bundle and prints the error-shaped report
        fail_json("unhandled", e)
        rc = 1
    sys.exit(rc)
